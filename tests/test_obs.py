"""Unified observability (obs/): tracer, registry, exporters.

What these tests pin, on the CPU/f64 suite:

* the span tracer's ring buffer: capacity bounds the memory, oldest
  spans evict first, and ``spans_total`` stays lifetime-exact through
  eviction (the windowed-trail + exact-count pattern);
* a GOLDEN Chrome trace for a 2-chunk pipelined serve with one injected
  retry on an injected clock — the exact (ph, name) event sequence,
  schema-validated (``ph``/``ts``/``dur``/``pid``/``tid``), proving the
  retry attempt, both dispatches, and the in-flight counter track are
  all visible in Perfetto;
* the ISSUE 5 acceptance run: PR 4's chaos plan under a tracer produces
  a Perfetto-loadable document in which retries, bisection, the breaker
  open -> half-open -> closed cycle, and fallback chunks are spans, and
  the SAME run's Prometheus exposition + JSON snapshot agree with
  ``ServeReport.metrics()`` on every shared counter (one backing store
  — they cannot disagree — but the contract is pinned here);
* the metrics registry: HPX-style name grammar to Prometheus sample
  translation, one-name-one-kind registration, windowed histograms and
  trails with lifetime-exact counts;
* the exporters: the 127.0.0.1 scrape endpoint serves both expositions
  live, and ``NLHEAT_EVENT_LOG`` streams discrete events as JSONL;
* the observability contract: recording never raises (a poisoned clock
  is swallowed), and the disabled path returns the shared no-op span.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from nonlocalheatequation_tpu.obs import trace as obs_trace
from nonlocalheatequation_tpu.obs.export import EventLog, serve_metrics
from nonlocalheatequation_tpu.obs.metrics import MetricsRegistry
from nonlocalheatequation_tpu.obs.trace import NULL_SPAN, Tracer
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)
from nonlocalheatequation_tpu.serve.server import ServePipeline
from nonlocalheatequation_tpu.utils.faults import FaultPlan

NX, NY, EPS, NSTEPS = 16, 16, 2, 2


def _cases(n, rng, nt=NSTEPS):
    return [EnsembleCase(shape=(NX, NY), nt=nt, eps=EPS, k=1.0, dt=1e-4,
                         dh=0.02, test=False,
                         u0=rng.normal(size=(NX, NY))) for _ in range(n)]


class TickClock:
    """Strictly-increasing injected clock: every read advances 1 ms, so
    span timestamps are deterministic without wall-clock racing."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 0.001
        return self.t


class StepClock:
    """Manually-advanced clock (the breaker-cooldown tests)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _check_schema(events):
    """Chrome trace-event schema: the fields Perfetto actually keys on."""
    assert events, "no events recorded"
    for ev in events:
        assert ev["ph"] in ("X", "i", "C"), ev
        assert isinstance(ev["name"], str) and ev["name"]
        assert isinstance(ev["cat"], str) and ev["cat"]
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        if ev["ph"] == "i":
            assert ev["s"] in ("t", "p", "g")


# -- tracer unit behavior ---------------------------------------------------
def test_ring_buffer_evicts_oldest_and_keeps_exact_lifetime_count():
    clock = TickClock()
    tr = Tracer(capacity=4, clock=clock)
    for i in range(10):
        t0 = clock()
        tr.complete(f"e{i}", t0)
    assert len(tr) == 4  # bounded
    assert [ev["name"] for ev in tr.events] == ["e6", "e7", "e8", "e9"]
    assert tr.spans_total == 10  # lifetime-exact through eviction
    doc = tr.chrome_trace()
    # metadata carries the merge identity (clock_sync/pid) — extra
    # top-level keys are legal Chrome trace format, ignored by Perfetto
    assert set(doc) == {"traceEvents", "displayTimeUnit", "metadata"}
    assert "clock_sync" in doc["metadata"]
    _check_schema(doc["traceEvents"])


def test_tracer_capacity_must_be_positive():
    with pytest.raises(ValueError, match="capacity"):
        Tracer(capacity=0)


def test_span_context_manager_records_error_and_timing():
    clock = TickClock()
    tr = Tracer(clock=clock)
    with tr.span("ok", cat="t", detail=1):
        pass
    with pytest.raises(RuntimeError):
        with tr.span("boom", cat="t"):
            raise RuntimeError("x")
    ok, boom = tr.events
    assert ok["name"] == "ok" and ok["args"] == {"detail": 1}
    assert ok["dur"] == pytest.approx(1000.0)  # one 1 ms tick, in us
    assert boom["args"]["error"] == "RuntimeError"


def test_disabled_path_is_the_shared_noop_span():
    assert obs_trace.get_tracer() is None  # the suite default
    assert obs_trace.span("anything", cat="x", a=1) is NULL_SPAN
    obs_trace.instant("anything")  # no tracer: silently dropped


def test_recording_never_raises_on_a_poisoned_clock():
    def bad_clock():
        raise RuntimeError("clock down")

    tr = Tracer(clock=bad_clock)
    with tr.span("s"):  # enter + exit both read the clock
        pass
    tr.instant("i")
    tr.counter("c", v=1)
    # untimeable events drop silently — the solve never notices
    assert tr.spans_total == 0
    tr.complete("caller-timed", 0.0, 1.0)  # caller timestamps still land
    assert tr.spans_total == 1


def test_write_failure_returns_false_never_raises(tmp_path, capsys):
    tr = Tracer()
    tr.complete("e", 0.0, 1.0)
    assert tr.write(str(tmp_path)) is False  # a directory: open() fails
    assert "trace write" in capsys.readouterr().err
    out = tmp_path / "t.json"
    assert tr.write(str(out)) is True
    _check_schema(json.load(open(out))["traceEvents"])


# -- the golden pipelined-serve trace ---------------------------------------
def test_golden_trace_two_chunk_pipelined_serve_with_one_retry():
    """ISSUE 5 satellite: deterministic spans for a 2-chunk pipelined
    serve with one injected retry, on an injected clock."""
    clock = TickClock()
    tracer = Tracer(clock=clock, pid=7)
    rng = np.random.default_rng(0)
    engine = EnsembleEngine(batch_sizes=(1,))
    with ServePipeline(engine=engine, depth=2, window_ms=0.0, clock=clock,
                       retries=1, backoff_ms=1.0, sleep=lambda s: None,
                       faults=FaultPlan.parse("raise@1"),
                       tracer=tracer) as pipe:
        for c in _cases(2, rng):
            pipe.submit(c)
        pipe.drain()
    events = list(tracer.events)
    _check_schema(events)
    # the golden sequence: chunk 0 dispatches clean; chunk 1's first
    # attempt raises (the injected fault), retries, dispatches; both are
    # IN FLIGHT together (the counter track reaches 2); then two fetches
    assert [(ev["ph"], ev["name"]) for ev in events] == [
        ("i", "serve.close"),      # chunk 0 closes (size trigger)
        ("X", "serve.build"),      # chunk 0 pad/build/stage
        ("i", "serve.dispatch"),   # chunk 0 async launch
        ("C", "serve.inflight"),   # 1 in flight
        ("i", "serve.close"),      # chunk 1 closes
        ("X", "serve.build"),      # chunk 1 attempt 1: injected raise
        ("i", "serve.retry"),      # classified + retried
        ("X", "serve.build"),      # chunk 1 attempt 2
        ("i", "serve.dispatch"),
        ("C", "serve.inflight"),   # 2 in flight — pipelining is real
        ("X", "serve.fetch"),      # chunk 0 retires (the due fence)
        ("C", "serve.inflight"),
        ("X", "serve.fetch"),      # chunk 1 retires
        ("C", "serve.inflight"),
    ]
    assert events[5]["args"]["error"] == "InjectedFault"
    retry = events[6]["args"]
    assert retry == {"chunk": 1, "attempt": 1, "classification": "error",
                     "backoff_ms": 1.0}
    assert events[7]["args"] == {"chunk": 1, "attempt": 2}
    assert [ev["args"]["inflight"] for ev in events
            if ev["ph"] == "C"] == [1, 2, 1, 0]
    assert all(ev["pid"] == 7 for ev in events)
    # injected clock: timestamps are monotone non-decreasing microseconds
    ts = [ev["ts"] for ev in events]
    assert ts == sorted(ts) and ts[0] > 0
    assert tracer.spans_total == len(events) == 14
    assert pipe.report.retries == 1


def test_bisection_and_quarantine_are_visible_as_spans():
    """An 8-case chunk with one persistent poison: the bisection chain
    (8 -> 4 -> 2 -> 1) and the quarantine land in the trace."""
    clock = TickClock()
    tracer = Tracer(clock=clock)
    rng = np.random.default_rng(3)
    engine = EnsembleEngine(batch_sizes=(8,))
    # huge window: the SIZE trigger closes one 8-case chunk
    with ServePipeline(engine=engine, depth=1, window_ms=10_000.0,
                       clock=clock, retries=0, backoff_ms=0.0,
                       fallback=False, sleep=lambda s: None,
                       faults=FaultPlan.parse("nan@c6x*"),
                       tracer=tracer) as pipe:
        handles = [pipe.submit(c) for c in _cases(8, rng)]
        pipe.drain()
    names = [ev["name"] for ev in tracer.events]
    assert names.count("serve.bisect") == pipe.report.bisections >= 3
    quar = [ev for ev in tracer.events if ev["name"] == "serve.quarantine"]
    assert len(quar) == 1
    assert quar[0]["args"]["case"] == 6
    assert quar[0]["args"]["classification"] == "corrupt"
    assert handles[6].error is not None
    assert all(h.result is not None for i, h in enumerate(handles) if i != 6)


def test_fetch_span_reports_effective_outcome_after_scan():
    """A fetched-ok payload the finite scan reclassifies as corrupt must
    not trace as outcome="ok": the serve.fetch span reports the
    EFFECTIVE outcome, matching the retry/quarantine instants beside
    it (the serve.fallback span already did)."""
    clock = TickClock()
    tracer = Tracer(clock=clock)
    rng = np.random.default_rng(5)
    engine = EnsembleEngine(batch_sizes=(1,))
    with ServePipeline(engine=engine, depth=1, window_ms=0.0, clock=clock,
                       retries=0, backoff_ms=0.0, fallback=False,
                       sleep=lambda s: None,
                       faults=FaultPlan.parse("nan@c0x*"),
                       tracer=tracer) as pipe:
        h = pipe.submit(_cases(1, rng)[0])
        pipe.drain()
    assert h.error is not None  # the single case quarantines
    fetches = [ev for ev in tracer.events if ev["name"] == "serve.fetch"]
    assert fetches and all(
        ev["args"]["outcome"] == "corrupt" for ev in fetches)


def test_traced_ab_baseline_ignores_a_process_global_tracer():
    # the untraced arm passes TRACE_OFF, not None: with a global tracer
    # installed (--trace/NLHEAT_TRACE) a None tracer would inherit it
    # and the A/B would trace both arms, gating on a vacuous ~1.0 ratio
    from nonlocalheatequation_tpu.serve.server import serve_traced_ab

    installed = Tracer()
    prev = obs_trace.set_tracer(installed)
    try:
        rng = np.random.default_rng(13)
        engine = EnsembleEngine(batch_sizes=(1,))
        serve_traced_ab(engine, _cases(1, rng), depth=1, iters=1)
    finally:
        obs_trace.set_tracer(prev)
    # the engine's one-off warmup build span belongs to the global
    # timeline; no PIPELINE span from either arm may leak there
    assert all(not ev["name"].startswith("serve.")
               for ev in installed.events)
    # and the sentinel itself forces the zero-cost path on a pipeline
    pipe = ServePipeline(engine=EnsembleEngine(batch_sizes=(1,)),
                         depth=1, tracer=obs_trace.TRACE_OFF)
    try:
        assert pipe._tracer is None
    finally:
        pipe.close()


def test_trace_write_degrades_exotic_span_args_to_str(tmp_path):
    # one non-JSON-serializable span arg must cost that arg its repr,
    # not the whole artifact (EventLog.emit's default=str discipline)
    from pathlib import Path

    tracer = Tracer(clock=TickClock())
    # np.float32 is NOT a float subclass — json.dump alone raises
    tracer.complete("serve.build", 0.001, 0.002, cat="serve",
                    rate=np.float32(0.25), where=Path("/x"))
    out = tmp_path / "t.json"
    assert tracer.write(str(out)) is True
    doc = json.loads(out.read_text())
    args = doc["traceEvents"][0]["args"]
    assert args["rate"] == "0.25" and args["where"] == "/x"


def test_trace_write_is_atomic_concurrent_writers_never_tear(tmp_path):
    # distributed ranks sharing a filesystem write via tmp + os.replace:
    # the artifact is always ONE writer's complete document, never
    # interleaved JSON Perfetto would reject — and no tmp file strands
    out = tmp_path / "host_trace.json"
    tracers = []
    for n in (3, 7):
        t = Tracer(clock=TickClock())
        for i in range(n):
            t.complete(f"serve.s{i}", 0.001 * (i + 1), 0.001 * (i + 2),
                       cat="serve")
        tracers.append(t)
    threads = [threading.Thread(target=t.write, args=(str(out),))
               for t in tracers]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    doc = json.loads(out.read_text())  # valid, complete
    assert len(doc["traceEvents"]) in (3, 7)
    assert list(tmp_path.iterdir()) == [out]


def test_serve_traced_ab_floors_iters_at_one():
    # iters <= 0 would return inf walls and a None tracer that bench.py
    # dereferences — the A/B must always measure at least once
    from nonlocalheatequation_tpu.serve.server import serve_traced_ab

    rng = np.random.default_rng(11)
    engine = EnsembleEngine(batch_sizes=(1,))
    compile_s, plain, traced, tracer, rep = serve_traced_ab(
        engine, _cases(1, rng), depth=1, iters=0)
    assert np.isfinite(plain) and np.isfinite(traced)
    assert tracer is not None and tracer.spans_total > 0
    assert rep is not None and rep.cases == 1


# -- the acceptance chaos run ----------------------------------------------
def test_chaos_trace_and_expositions_agree_with_report_metrics(tmp_path):
    """The ISSUE 5 acceptance: PR 4's chaos plan under a tracer yields a
    Perfetto-loadable trace showing retries, the breaker cycle, and the
    fallback chunks — and the run's Prometheus text + JSON snapshot
    agree with ``ServeReport.metrics()`` on every shared counter."""
    clock = StepClock()
    tracer = Tracer(clock=clock)
    rng = np.random.default_rng(7)
    cases = _cases(9, rng)
    engine = EnsembleEngine(batch_sizes=(1,))
    with ServePipeline(engine=engine, depth=3, window_ms=0.0, clock=clock,
                       retries=1, backoff_ms=0.0, fetch_deadline_ms=100.0,
                       breaker_threshold=1, breaker_cooldown_ms=50.0,
                       sleep=lambda s: None,
                       faults=FaultPlan.parse("raise@1,stall@3,nan@5,nan@c6x*"),
                       tracer=tracer) as pipe:
        for c in cases[:8]:
            pipe.submit(c)
        pipe.drain()
        clock.advance(0.1)  # breaker cooldown elapses
        pipe.submit(cases[8])  # the half-open probe
        pipe.drain()

    # -- the trace: every resilience mechanism is visible ------------------
    events = list(tracer.events)
    _check_schema(events)
    names = [ev["name"] for ev in events]
    assert names.count("serve.retry") == pipe.report.retries >= 1
    moves = [(ev["args"]["from"], ev["args"]["to"]) for ev in events
             if ev["name"] == "breaker.transition"]
    assert moves == [("closed", "open"), ("open", "half-open"),
                     ("half-open", "closed")]
    fallbacks = [ev for ev in events if ev["name"] == "serve.fallback"
                 and ev["args"]["outcome"] == "ok"]
    assert len(fallbacks) == pipe.report.fallback_chunks >= 1
    assert any(ev["name"] == "serve.quarantine"
               and ev["args"]["case"] == 6 for ev in events)
    # Perfetto-loadable: the written artifact is valid trace-event JSON
    out = tmp_path / "host_trace.json"
    assert tracer.write(str(out)) is True
    doc = json.load(open(out))
    assert doc["traceEvents"] and _check_schema(doc["traceEvents"]) is None

    # -- the expositions agree with metrics() on every shared counter ------
    m = pipe.metrics()
    res = m["resilience"]
    reg = pipe.registry
    snap = reg.snapshot()
    assert snap["/ensemble/cases"] == m["cases"]
    assert snap["/ensemble/dispatches"] == m["dispatches"]
    assert snap["/ensemble/buckets"] == m["buckets"]
    assert snap["/ensemble/programs-built"] == m["programs_built"]
    assert snap["/serve/retries"] == res["retries"]
    assert snap["/serve/bisections"] == res["bisections"]
    assert snap["/serve/fallback-chunks"] == res["fallback_chunks"]
    assert snap["/serve/faults"] == res["faults"]
    assert snap["/serve/quarantined"]["count"] == res["quarantined_total"]
    assert snap["/breaker/transitions"] == \
        res["breaker"]["transition_count"] == len(moves)
    assert snap["/serve/request-latency-ms"]["count"] == \
        m["requests_completed"]
    # one-line JSON snapshot round-trips to the same numbers
    assert json.loads(reg.snapshot_json()) == json.loads(json.dumps(
        snap, default=float))
    assert "\n" not in reg.snapshot_json()
    prom = reg.prometheus()
    assert f"nlheat_serve_retries {res['retries']}" in prom
    assert f"nlheat_ensemble_cases {m['cases']}" in prom
    assert (f"nlheat_breaker_transitions "
            f"{res['breaker']['transition_count']}") in prom
    for label, count in res["faults"].items():
        assert f'nlheat_serve_faults{{key="{label}"}} {count}' in prom


# -- metrics registry -------------------------------------------------------
def test_registry_kinds_and_one_name_one_kind():
    reg = MetricsRegistry()
    c = reg.counter("/serve/retries")
    c.inc()
    c.inc(2)
    assert reg.counter("/serve/retries") is c and c.value == 3
    g = reg.gauge("/serve/depth")
    g.set(4)
    assert g.value == 4
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("/serve/retries")
    with pytest.raises(ValueError, match="already registered"):
        reg.counter("/serve/depth")


def test_histogram_window_bounds_memory_count_stays_exact():
    reg = MetricsRegistry()
    h = reg.histogram("/serve/lat", window=8)
    for i in range(100):
        h.observe(float(i))
    assert len(h) == 8 and h.count == 100  # windowed + lifetime-exact
    assert h.total == sum(range(100))
    p = h.percentiles()
    assert p["max"] == 99.0 and p["p50"] >= 92.0  # the recent window
    t = reg.trail("/serve/log", window=4)
    for i in range(10):
        t.append({"i": i})
    assert [e["i"] for e in t] == [6, 7, 8, 9] and t.count == 10


def test_stable_copy_retries_racing_writer_then_defaults():
    # the exposition-side race guard: a RuntimeError (deque/dict mutated
    # during iteration) is retried; a persistent one falls back to the
    # default instead of raising out of a scrape handler
    from nonlocalheatequation_tpu.obs.metrics import _stable_copy

    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise RuntimeError("deque mutated during iteration")
        return [1, 2]

    assert _stable_copy(flaky, []) == [1, 2] and calls[0] == 3

    def hopeless():
        raise RuntimeError("deque mutated during iteration")

    assert _stable_copy(hopeless, {"d": 1}) == {"d": 1}


def test_expositions_survive_a_racing_recorder_thread():
    # the advertised mid-run scrape: the HTTP handler thread reads
    # prometheus()/snapshot_json() while the pipeline thread records —
    # deque/dict iteration must never leak a RuntimeError into a 500
    reg = MetricsRegistry()
    h = reg.histogram("/serve/request-latency-ms", window=64)
    lab = reg.labeled("/serve/faults")
    stop = threading.Event()

    def record():
        i = 0
        while not stop.is_set():
            h.observe(float(i % 97))
            lab[f"k{i % 13}"] = lab.get(f"k{i % 13}", 0) + 1
            i += 1

    w = threading.Thread(target=record)
    w.start()
    try:
        for _ in range(300):
            prom = reg.prometheus()
            assert "nlheat_serve_request_latency_ms_count" in prom
            json.loads(reg.snapshot_json())
    finally:
        stop.set()
        w.join()


def test_prometheus_name_grammar_instance_becomes_label():
    reg = MetricsRegistry()
    reg.gauge("/device{3}/busy-rate").set(0.25)
    reg.counter("/serve{chunk}/retries").inc(2)
    reg.labeled("/serve/faults")["hang"] = 5
    prom = reg.prometheus()
    assert 'nlheat_device_busy_rate{device="3"} 0.25' in prom
    assert 'nlheat_serve_retries{serve="chunk"} 2' in prom
    assert 'nlheat_serve_faults{key="hang"} 5' in prom
    assert "# TYPE nlheat_device_busy_rate gauge" in prom
    assert "# TYPE nlheat_serve_retries counter" in prom


def test_report_and_registry_share_one_storage():
    from nonlocalheatequation_tpu.serve.server import ServeReport

    r = ServeReport(depth=2)
    r.retries += 3
    r.faults["hang"] = r.faults.get("hang", 0) + 1
    assert r.registry.get("/serve/retries").value == 3
    assert r.registry.get("/serve/faults")["hang"] == 1
    r.registry.get("/serve/retries").inc()  # the other direction
    assert r.retries == 4
    # two reports never share counters (private registry each)
    assert ServeReport().retries == 0
    # the ISSUE 5 bound: every report window caps at LOG_CAP, so a
    # long-lived server cannot grow its report without bound
    from nonlocalheatequation_tpu.serve.server import LOG_CAP

    for w in (r.chunk_log.entries, r.occupancy_samples.entries,
              r.quarantined.entries, r.request_latency_ms.samples,
              r.queue_wait_ms.samples):
        assert w.maxlen == LOG_CAP


def test_publish_busy_rates_counts_windows_vs_actual_rebalances():
    from nonlocalheatequation_tpu.parallel.load_balance import (
        publish_busy_rates,
    )

    reg = MetricsRegistry()
    publish_busy_rates([0.2, 0.8], moved=0, registry=reg)  # ran, no moves
    publish_busy_rates([0.5, 0.5], moved=3, registry=reg)
    snap = reg.snapshot()
    assert snap["/balance/windows"] == 2
    assert snap["/balance/rebalances"] == 1  # only the window that moved
    assert snap["/balance/tiles-moved"] == 3
    assert snap["/device{0}/busy-rate"] == 0.5  # latest window's gauge


# -- exporters --------------------------------------------------------------
def test_scrape_endpoint_serves_both_expositions():
    reg = MetricsRegistry()
    reg.counter("/serve/retries").inc(3)
    srv = serve_metrics(0, reg)  # port 0: pick a free one
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "nlheat_serve_retries 3" in text
        js = json.loads(
            urllib.request.urlopen(f"{base}/metrics.json").read())
        assert js["/serve/retries"] == 3
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/other")
    finally:
        srv.close()


def test_scrape_endpoint_follows_a_live_registry_binding():
    holder = [MetricsRegistry()]
    srv = serve_metrics(0, lambda: holder[0])
    try:
        base = f"http://127.0.0.1:{srv.port}"
        holder[0].gauge("/serve/depth").set(1)
        js = json.loads(
            urllib.request.urlopen(f"{base}/metrics.json").read())
        assert js == {"/serve/depth": 1}
        holder[0] = MetricsRegistry()  # a new pipeline's registry
        holder[0].gauge("/serve/depth").set(8)
        js = json.loads(
            urllib.request.urlopen(f"{base}/metrics.json").read())
        assert js == {"/serve/depth": 8}
    finally:
        srv.close()


def test_event_log_streams_serve_events_as_jsonl(tmp_path, monkeypatch):
    path = tmp_path / "events.jsonl"
    monkeypatch.setenv("NLHEAT_EVENT_LOG", str(path))
    clock = TickClock()
    rng = np.random.default_rng(11)
    engine = EnsembleEngine(batch_sizes=(1,))
    with ServePipeline(engine=engine, depth=1, window_ms=0.0, clock=clock,
                       retries=1, backoff_ms=0.0, sleep=lambda s: None,
                       faults=FaultPlan.parse("raise@0")) as pipe:
        for c in _cases(2, rng):
            pipe.submit(c)
        pipe.drain()
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    kinds = [ln["event"] for ln in lines]
    assert kinds.count("retry") == pipe.report.retries == 1
    assert kinds.count("chunk") == 2  # one record per retired chunk
    assert lines[0]["classification"] == "error"


def test_event_log_unopenable_path_is_loud_but_not_fatal(tmp_path, capsys):
    log = EventLog.from_env(
        {"NLHEAT_EVENT_LOG": str(tmp_path / "no" / "dir" / "x.jsonl")})
    assert log is None
    assert "cannot be opened" in capsys.readouterr().err
    assert EventLog.from_env({}) is None  # unset: the zero-cost path


def test_event_log_emit_is_thread_safe_one_json_per_line(tmp_path):
    path = tmp_path / "e.jsonl"
    log = EventLog(str(path))
    threads = [threading.Thread(
        target=lambda i=i: [log.emit(event="t", thread=i, n=j)
                            for j in range(50)]) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.close()
    lines = path.read_text().splitlines()
    assert len(lines) == 200
    assert all(json.loads(ln)["event"] == "t" for ln in lines)
