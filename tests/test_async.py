"""Async (pipelined) variant: the reference's full ctest table + throttle.

The reference runs 9 cases through its async binary (CMakeLists.txt:124-138,
tests/2d_async.txt), each tiling the global (nx*np) x (ny*np) grid and
throttling the task pipeline with a sliding semaphore of depth nd
(src/2d_nonlocal_async.cpp:410, 442-451).  Here the analog is the jit solver
with an nd-deep async dispatch queue (models/solver2d.py), so every table row
runs with nd set, plus a behavioral test that the in-flight count is actually
bounded by nd and actually reaches it (the throttle exists and engages).
"""

import pytest

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from tests.cases import CASES_2D_ASYNC, L2_THRESHOLD


@pytest.mark.parametrize("nx,ny,np_,nt,eps,k,dt,dh", CASES_2D_ASYNC)
def test_async_batch_case(nx, ny, np_, nt, eps, k, dt, dh):
    gx, gy = nx * np_, ny * np_
    s = Solver2D(gx, gy, nt, eps, k=k, dt=dt, dh=dh, backend="jit",
                 method="conv", nd=5)
    s.test_init()
    s.do_work()
    assert s.error_l2 / (gx * gy) <= L2_THRESHOLD


@pytest.mark.parametrize("nd", [1, 3])
def test_dispatch_throttle_bounds_inflight(nd):
    s = Solver2D(20, 20, 12, eps=3, k=0.2, dt=0.001, dh=0.02,
                 backend="jit", method="conv", nd=nd)
    s.test_init()
    s.do_work()
    # bounded by nd, and the pipeline actually fills to nd (nt >> nd)
    assert s.max_inflight_ == nd


def test_throttled_equals_unthrottled():
    """nd only paces dispatch; the numerics must be bit-identical."""
    import numpy as np

    runs = []
    for nd in (None, 2):
        s = Solver2D(20, 20, 10, eps=3, k=0.2, dt=0.001, dh=0.02,
                     backend="jit", method="conv", nd=nd)
        s.test_init()
        runs.append(s.do_work())
    # nd=None takes the one-scan fast path, nd=2 the per-step path; both jit
    # the same step numerics
    np.testing.assert_allclose(runs[0], runs[1], rtol=0, atol=1e-12)
