"""Checkpoint/resume — framework extension (the reference has none,
SURVEY.md section 5). Contract: interrupted + resumed == uninterrupted,
bit-for-bit, and parameter mismatches refuse to resume."""

import numpy as np
import pytest

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.utils import checkpoint as ckpt


def _solver(nt, **kw):
    return Solver2D(20, 20, nt, eps=3, k=1.0, dt=1e-4, dh=0.05,
                    backend="jit", **kw)


def test_roundtrip(tmp_path):
    path = str(tmp_path / "state.npz")
    u = np.random.default_rng(0).normal(size=(5, 7))
    ckpt.save_state(path, u, 13, {"eps": 3})
    u2, t, params = ckpt.load_state(path)
    assert t == 13 and params["eps"] == 3
    assert (u2 == u).all()


def test_interrupted_equals_uninterrupted(tmp_path):
    path = str(tmp_path / "state.npz")
    full = _solver(20)
    full.test_init()
    full.do_work()

    first = _solver(20, checkpoint_path=path, ncheckpoint=10)
    first.test_init()
    first.nt = 10  # "crash" after 10 steps; checkpoint at t=10 exists
    first.do_work()

    second = _solver(20)
    second.test_init()
    second.resume(path)
    assert second.t0 == 10
    second.do_work()

    assert (second.u == full.u).all()  # bit-for-bit
    assert second.error_l2 == pytest.approx(full.error_l2)


def test_param_mismatch_refuses(tmp_path):
    path = str(tmp_path / "state.npz")
    s = _solver(10, checkpoint_path=path, ncheckpoint=5)
    s.test_init()
    s.do_work()
    other = Solver2D(20, 20, 20, eps=4, k=1.0, dt=1e-4, dh=0.05, backend="jit")
    other.test_init()
    with pytest.raises(ValueError, match="mismatch"):
        other.resume(path)


def test_version_guard(tmp_path):
    path = str(tmp_path / "state.npz")
    ckpt.save_state(path, np.zeros((2, 2)), 0, {})
    import numpy as _np

    with _np.load(path) as z:
        data = dict(z)
    data["version"] = _np.int64(99)
    with open(path, "wb") as f:
        _np.savez(f, **data)
    with pytest.raises(ValueError, match="version"):
        ckpt.load_state(path)


def test_truncated_checkpoint_refused_loudly_with_hint(tmp_path):
    # the torn-write shapes: a file cut at any point must refuse with the
    # typed ValueError carrying the resume-from-previous hint — never a
    # bare zipfile/KeyError stack trace, never a silent partial resume
    path = str(tmp_path / "state.npz")
    u = np.random.default_rng(1).normal(size=(16, 16))
    ckpt.save_state(path, u, 7, {"eps": 3})
    blob = open(path, "rb").read()
    for cut in (0, 10, len(blob) // 2, len(blob) - 8):
        with open(path, "wb") as f:
            f.write(blob[:cut])
        with pytest.raises(ValueError, match="previous checkpoint"):
            ckpt.load_state(path)


def test_corrupt_payload_fails_integrity_check(tmp_path):
    # bit rot INSIDE a structurally valid archive: npz stores arrays
    # uncompressed, so a flipped state byte survives unzip — only the
    # crc marker catches it
    path = str(tmp_path / "state.npz")
    u = np.random.default_rng(2).normal(size=(16, 16))
    ckpt.save_state(path, u, 7, {"eps": 3})
    blob = bytearray(open(path, "rb").read())
    # flip one byte in the middle of the (large, uncompressed) u payload
    blob[len(blob) // 2] ^= 0xFF
    with open(path, "wb") as f:
        f.write(bytes(blob))
    with pytest.raises(ValueError, match="integrity|previous checkpoint"):
        ckpt.load_state(path)


def test_kill_mid_write_leaves_previous_checkpoint_loadable(tmp_path,
                                                           monkeypatch):
    # the crash-safety contract: a kill at ANY point of save_state leaves
    # the previous checkpoint intact and loadable, and strands no tmp
    # files next to it
    path = str(tmp_path / "state.npz")
    u1 = np.random.default_rng(3).normal(size=(8, 8))
    ckpt.save_state(path, u1, 5, {"eps": 3})

    # kill #1: mid-serialization (np.savez dies after writing some bytes)
    def _dying_savez(f, **kw):
        f.write(b"partial garbage")
        raise KeyboardInterrupt  # the signal-shaped interruption

    monkeypatch.setattr(ckpt.np, "savez", _dying_savez)
    with pytest.raises(KeyboardInterrupt):
        ckpt.save_state(path, np.zeros((8, 8)), 6, {"eps": 3})
    monkeypatch.undo()

    # kill #2: after the tmp write, before the atomic publish
    monkeypatch.setattr(ckpt.os, "replace",
                        lambda *a: (_ for _ in ()).throw(KeyboardInterrupt))
    with pytest.raises(KeyboardInterrupt):
        ckpt.save_state(path, np.zeros((8, 8)), 6, {"eps": 3})
    monkeypatch.undo()

    u2, t, params = ckpt.load_state(path)  # the previous state survives
    assert t == 5 and (u2 == u1).all() and params["eps"] == 3
    leftovers = [p.name for p in tmp_path.iterdir() if ".tmp." in p.name]
    assert leftovers == []


def test_v1_checkpoint_without_crc_still_loads(tmp_path):
    # back-compat: pre-marker (v1) checkpoints keep resuming
    import json as _json

    path = str(tmp_path / "state.npz")
    u = np.arange(6.0).reshape(2, 3)
    with open(path, "wb") as f:
        np.savez(f, u=u, t=np.int64(4), version=np.int64(1),
                 params=np.frombuffer(_json.dumps({"eps": 2}).encode(),
                                      dtype=np.uint8))
    u2, t, params = ckpt.load_state(path)
    assert t == 4 and (u2 == u).all() and params["eps"] == 2


def test_distributed_interrupted_equals_uninterrupted(tmp_path):
    from nonlocalheatequation_tpu.parallel.distributed2d import Solver2DDistributed
    from nonlocalheatequation_tpu.parallel.mesh import make_mesh

    def solver(nt, **kw):
        return Solver2DDistributed(10, 10, 2, 2, nt, eps=3, k=1.0, dt=1e-4,
                                   dh=0.05, mesh=make_mesh(2, 2), **kw)

    path = str(tmp_path / "dist.npz")
    full = solver(20)
    full.test_init()
    full.do_work()

    first = solver(20, checkpoint_path=path, ncheckpoint=10)
    first.test_init()
    first.nt = 10  # "crash" after 10 steps
    first.do_work()

    second = solver(20)
    second.test_init()
    second.resume(path)
    assert second.t0 == 10
    second.do_work()
    assert (second.u == full.u).all()


def test_elastic_interrupted_equals_uninterrupted(tmp_path):
    from nonlocalheatequation_tpu.parallel.elastic import ElasticSolver2D

    def solver(nt, **kw):
        return ElasticSolver2D(5, 5, 4, 4, nt, eps=3, k=0.2, dt=1e-4,
                               dh=0.05, **kw)

    path = str(tmp_path / "elastic.npz")
    full = solver(16)
    full.test_init()
    full.do_work()

    first = solver(16, checkpoint_path=path, ncheckpoint=8)
    first.test_init()
    first.nt = 8
    first.do_work()

    second = solver(16)
    second.test_init()
    second.resume(path)
    assert second.t0 == 8
    second.do_work()
    assert (second.u == full.u).all()


def test_cli_distributed_checkpoint_resume(tmp_path, capsys):
    from nonlocalheatequation_tpu.cli import solve2d_distributed

    path = str(tmp_path / "d.npz")
    base = ["--nx", "10", "--ny", "10", "--npx", "2", "--npy", "2",
            "--eps", "3", "--dt", "1e-4", "--dh", "0.05",
            "--cmp", "false", "--no-header"]
    rc = solve2d_distributed.main(
        base + ["--nt", "10", "--checkpoint", path, "--ncheckpoint", "5"])
    assert rc == 0
    rc = solve2d_distributed.main(
        base + ["--nt", "20", "--checkpoint", path, "--resume"])
    assert rc == 0
    assert "l2:" in capsys.readouterr().out


def test_cli_checkpoint_resume(tmp_path, capsys):
    from nonlocalheatequation_tpu.cli import solve2d

    path = str(tmp_path / "c.npz")
    base = ["--nx", "20", "--ny", "20", "--eps", "3", "--dt", "1e-4",
            "--dh", "0.05", "--test", "--cmp", "false", "--no-header"]
    rc = solve2d.main(base + ["--nt", "10", "--checkpoint", path,
                              "--ncheckpoint", "5"])
    assert rc == 0
    rc = solve2d.main(base + ["--nt", "20", "--checkpoint", path, "--resume"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "l2:" in out


def test_legacy_nx_ny_params_translate_to_shape(tmp_path):
    # checkpoints written before the schema moved to a 'shape' list carried
    # nx/ny keys; they must keep resuming (ADVICE r2)
    path = str(tmp_path / "state.npz")
    s = _solver(10, checkpoint_path=None, ncheckpoint=0)
    s.test_init()
    legacy = {k: v for k, v in s._ckpt_params().items() if k != "shape"}
    legacy["nx"], legacy["ny"] = s._grid_shape
    ckpt.save_state(path, np.asarray(s.u0), 0, legacy)
    _, _, params = ckpt.load_state(path)
    assert params["shape"] == list(s._grid_shape)
    s.resume(path)  # must not raise "'shape' missing"
    assert s.t0 == 0


def test_solver3d_checkpoint_resume_bit_identical(tmp_path):
    from nonlocalheatequation_tpu.models.solver3d import Solver3D

    path = str(tmp_path / "c3.npz")

    def make(**kw):
        return Solver3D(10, 10, 10, 12, eps=2, k=0.5, dt=1e-4, dh=0.1,
                        backend="jit", **kw)

    full = make()
    full.test_init()
    full.do_work()
    first = make(checkpoint_path=path, ncheckpoint=5)
    first.test_init()
    first.nt = 7  # crash after the t=4 checkpoint
    first.do_work()
    second = make(checkpoint_path=path, ncheckpoint=5)
    second.test_init()
    second.resume(path)
    second.do_work()
    assert np.array_equal(full.u, second.u)


def test_unstructured_checkpoint_resume_bit_identical(tmp_path):
    from nonlocalheatequation_tpu.ops.unstructured import (
        UnstructuredNonlocalOp,
        UnstructuredSolver,
    )

    path = str(tmp_path / "cu.npz")
    rng = np.random.default_rng(0)
    m, h = 12, 1.0 / 12
    xs, ys = np.meshgrid(np.arange(m) * h, np.arange(m) * h, indexing="ij")
    pts = np.stack([xs.ravel(), ys.ravel()], axis=1)
    pts += rng.uniform(-0.2 * h, 0.2 * h, pts.shape)
    op = UnstructuredNonlocalOp(pts, 2.8 * h, k=0.5, dt=1e-5, vol=h * h)

    full = UnstructuredSolver(op, nt=12)
    full.test_init()
    full.do_work()
    first = UnstructuredSolver(op, nt=12, checkpoint_path=path, ncheckpoint=5)
    first.test_init()
    first.nt = 7
    first.do_work()
    second = UnstructuredSolver(op, nt=12, checkpoint_path=path,
                                ncheckpoint=5)
    second.test_init()
    second.resume(path)
    second.do_work()
    assert np.array_equal(full.u, second.u)


def test_unstructured_checkpoint_param_mismatch_refuses(tmp_path):
    from nonlocalheatequation_tpu.ops.unstructured import (
        UnstructuredNonlocalOp,
        UnstructuredSolver,
    )

    path = str(tmp_path / "cu2.npz")
    rng = np.random.default_rng(1)
    pts = rng.uniform(size=(64, 2))
    op = UnstructuredNonlocalOp(pts, 0.2, k=0.5, dt=1e-5, vol=1.0 / 64)
    s = UnstructuredSolver(op, nt=6, checkpoint_path=path, ncheckpoint=3)
    s.test_init()
    s.do_work()
    op2 = UnstructuredNonlocalOp(pts, 0.3, k=0.5, dt=1e-5, vol=1.0 / 64)
    other = UnstructuredSolver(op2, nt=6)
    other.test_init()
    with pytest.raises(ValueError):
        other.resume(path)


def test_distributed3d_checkpoint_resume_bit_identical(tmp_path):
    """Sharded 3D checkpoint round-trip, and portability: the serial 3D
    solver resumes a checkpoint the distributed solver wrote."""
    from nonlocalheatequation_tpu.models.solver3d import Solver3D
    from nonlocalheatequation_tpu.parallel.distributed3d import (
        Solver3DDistributed,
    )

    path = str(tmp_path / "d3.npz")

    def make(**kw):
        return Solver3DDistributed(8, 8, 8, 12, eps=2, k=0.5, dt=1e-4,
                                   dh=0.125, **kw)

    full = make()
    full.test_init()
    full.do_work()
    first = make(checkpoint_path=path, ncheckpoint=5)
    first.test_init()
    first.nt = 7
    first.do_work()
    second = make(checkpoint_path=path, ncheckpoint=5)
    second.test_init()
    second.resume(path)
    second.do_work()
    assert np.array_equal(full.u, second.u)

    serial = Solver3D(8, 8, 8, 12, eps=2, k=0.5, dt=1e-4, dh=0.125,
                      backend="jit")
    serial.test_init()
    serial.resume(path)  # cross-solver portability on the same global grid
    serial.do_work()
    assert np.abs(serial.u - full.u).max() < 1e-12
