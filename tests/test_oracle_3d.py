"""3D solver tests — framework extension (no 3D exists in the reference;
the discretization applies the reference's recipe once more per axis and is
held to the same manufactured-solution contract)."""

import numpy as np
import pytest

from tests.cases import L2_THRESHOLD

from nonlocalheatequation_tpu.models.solver3d import Solver3D
from nonlocalheatequation_tpu.ops.nonlocal_op import NonlocalOp3D
from nonlocalheatequation_tpu.ops.stencil import horizon_mask_3d

# nx ny nz nt eps k dt dh — scaled-down 3D analogs of the tests/2d.txt cases
CASES_3D = [
    (16, 16, 16, 20, 3, 1.0, 0.0005, 0.0625),
    (12, 12, 12, 40, 2, 1.0, 0.0002, 1.0 / 12),
    (16, 12, 8, 20, 3, 0.5, 0.0005, 0.05),
    (6, 6, 6, 10, 8, 1.0, 0.0001, 1.0 / 6),   # eps > grid: degenerate halo
]


@pytest.mark.parametrize("nx,ny,nz,nt,eps,k,dt,dh", CASES_3D)
def test_batch_case_oracle(nx, ny, nz, nt, eps, k, dt, dh):
    s = Solver3D(nx, ny, nz, nt, eps, k=k, dt=dt, dh=dh, backend="oracle")
    s.test_init()
    s.do_work()
    assert s.error_l2 / (nx * ny * nz) <= L2_THRESHOLD


@pytest.mark.parametrize("method", ["shift", "sat"])
def test_jit_matches_oracle(method):
    nx, ny, nz, nt, eps, k, dt, dh = CASES_3D[0]
    ref = Solver3D(nx, ny, nz, nt, eps, k=k, dt=dt, dh=dh, backend="oracle")
    ref.test_init()
    ref.do_work()
    s = Solver3D(nx, ny, nz, nt, eps, k=k, dt=dt, dh=dh, backend="jit",
                 method=method)
    s.test_init()
    s.do_work()
    assert np.abs(s.u - ref.u).max() < 1e-11


def test_sphere_raster_shape():
    m = horizon_mask_3d(3)
    assert m.shape == (7, 7, 7)
    # exactly the integer lattice ball i^2+j^2+k^2 <= 9
    i = np.arange(-3, 4)
    expect = (i[:, None, None] ** 2 + i[None, :, None] ** 2
              + i[None, None, :] ** 2) <= 9
    assert (m == expect).all()


def test_methods_agree_random_field():
    rng = np.random.default_rng(0)
    u = rng.normal(size=(10, 12, 14))
    a = NonlocalOp3D(3, 1.0, 1e-4, 0.05, method="shift")
    b = NonlocalOp3D(3, 1.0, 1e-4, 0.05, method="sat")
    import jax.numpy as jnp

    x = jnp.asarray(u)
    assert float(abs(a.neighbor_sum(x) - b.neighbor_sum(x)).max()) < 1e-10
    assert np.abs(a.neighbor_sum_np(u) - np.asarray(a.neighbor_sum(x))).max() < 1e-10


def test_operator_converges_to_laplacian():
    # c_3d moment-matching: L(G) -> k * laplace(G) for smooth G as eps*dh -> 0.
    # This guards against a factor-level error in the constant; the discrete
    # sphere's moment bias decays with eps and horizon (9% at eps=4/dh=1/64,
    # 3% here).
    eps, n, dh = 6, 64, 1.0 / 128
    op = NonlocalOp3D(eps, k=1.0, dt=1e-4, dh=dh, method="shift")
    g = op.spatial_profile(n, n, n)
    lg = op.apply_np(g)
    # interior points only (away from the boundary collar)
    lap = -3.0 * (2 * np.pi) ** 2 * g  # exact laplacian of sin*sin*sin
    c = slice(2 * eps, n - 2 * eps)
    rel = np.abs(lg[c, c, c] - lap[c, c, c]).max() / np.abs(lap[c, c, c]).max()
    assert rel < 0.05


def test_cli_batch(tmp_path, capsys):
    from nonlocalheatequation_tpu.cli import solve3d

    import io
    import sys

    old = sys.stdin
    sys.stdin = io.StringIO("1\n12 12 12 10 2 1 0.0002 0.0833333333\n")
    try:
        rc = solve3d.main(["--test_batch"])
    finally:
        sys.stdin = old
    assert rc == 0
    assert "Tests Passed" in capsys.readouterr().out


def test_cli_superstep_requires_distributed(capsys):
    from nonlocalheatequation_tpu.cli import solve3d

    rc = solve3d.main(["--superstep", "2", "--nt", "2"])
    assert rc == 1
    assert "requires --distributed" in capsys.readouterr().err


def test_cli_distributed_superstep_batch(capsys):
    from nonlocalheatequation_tpu.cli import solve3d

    import io
    import sys

    old = sys.stdin
    sys.stdin = io.StringIO("1\n12 12 12 10 2 1 0.0002 0.0833333333\n")
    try:
        rc = solve3d.main(["--test_batch", "--distributed",
                           "--superstep", "3"])
    finally:
        sys.stdin = old
    assert rc == 0
    assert "Tests Passed" in capsys.readouterr().out
