"""Pallas CSR strip-gather tier + mesh-hash serving (ISSUE 17).

Contracts pinned here, all on the f64 8-virtual-device CPU suite
(tests/conftest.py; off-TPU the kernels run in interpreter mode, so the
real kernel BODY executes):

* the strip-gather ``L(u)`` is <= 1e-12 of the ``segment_sum`` oracle
  (ops/unstructured.py ``layout="edges"``) across dtypes, and the bf16
  pair-frame tier equals the oracle applied to a bf16-rounded state —
  the ``_bf16_round`` operand semantic of ops/nonlocal_op.py;
* on a uniform grid-shaped cloud with the grid constant the kernel is
  pinned <= 1e-12 to the 2-D grid stencil interior (ops/stencil.py via
  NonlocalOp2D), and a registered grid mesh holds the manufactured
  ``error_l2/#points <= 1e-6`` contract through the ensemble engine;
* the scan-carried multi-step form equals the iterated per-step form,
  and each batched lane is bit-identical to its solo scan;
* repeat mesh-hash traffic WARM-BOOTS: second engine on the same mesh
  + shared AOT store loads with zero programs built (store hits >= 1)
  bit-identically — and the same spy holds through the replica-router
  path (a fresh worker process booting from the shared store);
* the picker's mesh axis picks the gather tier under the mesh's real
  forward-Euler bound ``1 / max(c_i * wsum_i)``;
* the ``POST /v1/meshes`` front door: upload -> meta -> mesh-keyed
  solve bit-identical to the direct engine; malformed and oversized
  uploads are refused loudly (400, Content-Length checked before any
  body byte is read), unknown hashes 404.
"""

import http.client
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tests.cases import L2_THRESHOLD

from nonlocalheatequation_tpu.ops.nonlocal_op import NonlocalOp2D
from nonlocalheatequation_tpu.ops.pallas_gather import (
    build_gather_L,
    make_batched_gather_multi_step_fn,
    make_gather_multi_step_fn,
    make_gather_step_fn,
    pack_strips,
)
from nonlocalheatequation_tpu.ops.unstructured import UnstructuredNonlocalOp
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
    run_test_cases,
)
from nonlocalheatequation_tpu.serve.meshes import MeshStore, gang_order

assert jax.config.jax_enable_x64  # the oracle contract (conftest forces it)


def cloud(n=120, seed=7):
    """Random planar cloud with a variable horizon (factor ~1.5)."""
    rng = np.random.default_rng(seed)
    pts = rng.uniform(size=(n, 2))
    eps = 0.12 * (1.0 + 0.5 * rng.uniform(size=n))
    return pts, eps


def grid_cloud(n, dh):
    ii, jj = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    return np.stack([ii.ravel() * dh, jj.ravel() * dh], axis=1)


def bf16_round(u):
    return np.asarray(jnp.asarray(u).astype(jnp.bfloat16), np.float64)


# -- kernel parity vs the segment_sum oracle --------------------------------


@pytest.mark.parametrize("dtype", ["float64", "float32"])
def test_gather_matches_segment_sum_oracle(dtype):
    pts, eps = cloud()
    op = UnstructuredNonlocalOp(pts, eps, k=1.0, dt=1e-4, vol=1.0 / 120)
    rng = np.random.default_rng(1)
    u = rng.normal(size=op.n)
    want = np.asarray(op.apply(jnp.asarray(u), layout="edges"), np.float64)
    got = np.asarray(build_gather_L(op, dtype)(jnp.asarray(u)), np.float64)
    scale = np.abs(want).max()
    tol = 1e-12 if dtype == "float64" else 1e-5
    assert np.abs(got - want).max() <= tol * scale


def test_bf16_pair_frame_matches_rounded_oracle():
    pts, eps = cloud(seed=11)
    op = UnstructuredNonlocalOp(pts, eps, k=1.0, dt=1e-4, vol=1.0 / 120)
    rng = np.random.default_rng(2)
    u = rng.normal(size=op.n)
    # the tier rounds the gathered STATE once (center entry included,
    # since the center rides as a baked column); weights and the row
    # reduction stay in the f64 carry — so the oracle is the exact
    # edges-layout apply of the rounded state
    want = np.asarray(
        op.apply(jnp.asarray(bf16_round(u)), layout="edges"), np.float64)
    got = np.asarray(
        build_gather_L(op, "float64", "bf16")(jnp.asarray(u)), np.float64)
    scale = np.abs(want).max()
    assert np.abs(got - want).max() <= 1e-12 * scale
    # and the rounding is actually engaged (differs from the f32 tier)
    full = np.asarray(build_gather_L(op, "float64")(jnp.asarray(u)))
    assert np.abs(full - got).max() > 0


def test_grid_cloud_matches_stencil_interior():
    n, eps, dh = 16, 3, 1.0 / 16
    gop = NonlocalOp2D(eps, k=1.0, dt=1e-4, dh=dh, method="shift")
    uop = UnstructuredNonlocalOp(
        grid_cloud(n, dh), eps * dh, k=1.0, dt=1e-4, vol=dh * dh, c=gop.c)
    rng = np.random.default_rng(0)
    u = rng.normal(size=(n, n))
    a = gop.apply_np(u)
    b = np.asarray(
        build_gather_L(uop, "float64")(jnp.asarray(u.ravel()))).reshape(n, n)
    interior = (slice(eps, n - eps),) * 2
    scale = np.abs(a[interior]).max()
    assert np.abs(a[interior] - b[interior]).max() <= 1e-12 * scale


def test_strip_pack_is_cached_on_op():
    pts, eps = cloud(n=40, seed=3)
    op = UnstructuredNonlocalOp(pts, eps, k=1.0, dt=1e-4, vol=1.0 / 40)
    a = pack_strips(op, "float64")
    assert pack_strips(op, "float64") is a  # edge set immutable -> cached
    col, w, tm, n_pad, n_upad = a
    assert col.shape == w.shape and n_pad % tm == 0 and n_upad % 128 == 0


def test_gather_rejects_unknown_precision():
    pts, eps = cloud(n=24, seed=4)
    op = UnstructuredNonlocalOp(pts, eps, k=1.0, dt=1e-4, vol=1.0 / 24)
    with pytest.raises(ValueError, match="precision"):
        build_gather_L(op, "float64", "f16")


# -- step forms: scan == iterated, batched lane == solo ---------------------


def test_multi_step_equals_iterated_steps():
    pts, eps = cloud(n=80, seed=5)
    op = UnstructuredNonlocalOp(pts, eps, k=1.0, dt=1e-5, vol=1.0 / 80)
    step = make_gather_step_fn(op, test=True)
    multi = make_gather_multi_step_fn(op, nt=5, test=True)
    rng = np.random.default_rng(6)
    u0 = rng.normal(size=op.n)
    u = jnp.asarray(u0)
    for t in range(5):
        u = step(u, jnp.asarray(t))
    got = np.asarray(multi(jnp.asarray(u0), 0))
    want = np.asarray(u)
    scale = np.abs(want).max()
    assert np.abs(got - want).max() <= 1e-12 * scale


def test_batched_lane_bit_identical_to_solo():
    pts, eps = cloud(n=64, seed=8)
    ops = [UnstructuredNonlocalOp(pts, eps, k=k, dt=1e-5, vol=1.0 / 64)
           for k in (0.5, 1.0, 2.0)]
    rng = np.random.default_rng(9)
    U0 = rng.normal(size=(3, 64))
    batched = make_batched_gather_multi_step_fn(ops, nt=4)
    got = np.asarray(batched(jnp.asarray(U0), 0))
    for b, op in enumerate(ops):
        solo = np.asarray(
            make_gather_multi_step_fn(op, nt=4)(jnp.asarray(U0[b]), 0))
        assert np.array_equal(got[b], solo)  # stacked lane == solo scan


# -- mesh-hash serving: engine, warm boot, picker ---------------------------


def _register_grid_mesh(tmp_path, n=20):
    dh = 1.0 / n
    store = MeshStore(str(tmp_path / "meshes"))
    mhash = store.put(grid_cloud(n, dh), 3 * dh, dh * dh)
    return store, mhash, n * n


def test_engine_mesh_case_manufactured_contract(tmp_path, monkeypatch):
    store, mhash, nn = _register_grid_mesh(tmp_path)
    monkeypatch.setenv("NLHEAT_MESH_DIR", store.root)
    case = EnsembleCase(shape=(nn,), nt=20, eps=0, k=1.0, dt=1e-4,
                        dh=0.0, test=True, mesh=mhash)
    (err2, n), = run_test_cases([case])
    assert n == nn and err2 / n <= L2_THRESHOLD


def test_mesh_warm_boot_zero_retrace_bit_identical(tmp_path, monkeypatch):
    store, mhash, nn = _register_grid_mesh(tmp_path)
    monkeypatch.setenv("NLHEAT_MESH_DIR", store.root)
    rng = np.random.default_rng(10)
    cases = [EnsembleCase(shape=(nn,), nt=4, eps=0, k=1.0, dt=1e-5,
                          dh=0.0, test=False, u0=rng.normal(size=nn),
                          mesh=mhash)
             for _ in range(2)]
    d = str(tmp_path / "store")
    cold_eng = EnsembleEngine(program_store=d)
    cold = cold_eng.run(cases)
    assert cold_eng.report.programs_built >= 1
    assert cold_eng.program_store.stats()["saves"] >= 1
    warm_eng = EnsembleEngine(program_store=d)
    warm = warm_eng.run(cases)
    # the zero-retrace spy: the stored executable IS the program
    assert warm_eng.report.programs_built == 0
    assert warm_eng.report.programs_loaded >= 1
    assert warm_eng.program_store.stats()["hits"] >= 1
    assert set(warm_eng.report.strategies.values()) == {"stored"}
    for a, b in zip(cold, warm, strict=True):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_picker_mesh_axis_picks_gather(tmp_path):
    from nonlocalheatequation_tpu.serve.meshes import get_mesh_op
    from nonlocalheatequation_tpu.serve.picker import pick_engine

    store, mhash, nn = _register_grid_mesh(tmp_path)
    ch = pick_engine((1,), 0, 1.0, 1.0, T_final=5e-4, accuracy=1e-5,
                     mesh=mhash, mesh_dir=store.root)
    assert ch.method == "gather" and ch.stepper == "euler"
    assert ch.precision in ("f32", "bf16")
    # dt honors the mesh's REAL per-point forward-Euler bound
    op = get_mesh_op(mhash, 1.0, 1.0, mesh_dir=store.root)
    assert ch.dt <= 0.8 / float(np.max(op.c * op.wsum)) + 1e-15


def test_gang_order_partitions_contiguously():
    pts, _ = cloud(n=256, seed=12)
    perm = gang_order(pts, 4)
    assert sorted(perm) == list(range(256))  # a true permutation

    # each device's contiguous index block must be MORE spatially
    # compact than under mesh-file order (the RCB cut's whole point:
    # the sharded operator partitions by index, so block bounding-box
    # area is a proxy for the halo each device exchanges)
    def area(order):
        return sum(float(np.prod(np.ptp(pts[order[lo:lo + 64]], axis=0)))
                   for lo in range(0, 256, 64))

    assert area(perm) < 0.5 * area(np.arange(256))


# -- front door + router warm boot (one fleet spawn, batched asserts) -------


def _req(port, path, body=None, method=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(
        url, data=data, method=method or ("POST" if data else "GET"))
    try:
        with urllib.request.urlopen(r) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_mesh_http_and_router_warm_boot(tmp_path, monkeypatch):
    from nonlocalheatequation_tpu.serve.http import IngressServer
    from nonlocalheatequation_tpu.serve.router import ReplicaRouter

    mdir = str(tmp_path / "meshes")
    sdir = str(tmp_path / "store")
    pts, eps = cloud()
    case_body = {"mesh": None, "nt": 5, "k": 1.0, "dt": 1e-4, "test": True}
    with ReplicaRouter(replicas=1, mesh_dir=mdir,
                       program_store=sdir) as router:
        srv = IngressServer(0, router, mesh_dir=mdir)
        try:
            st, meta = _req(srv.port, "/v1/meshes",
                            {"points": pts.tolist(), "eps": eps.tolist()})
            assert st == 201 and meta["nodes"] == 120
            mhash = meta["hash"]
            st, m2 = _req(srv.port, f"/v1/meshes/{mhash}")
            assert st == 200 and m2 == meta
            st, e = _req(srv.port, "/v1/meshes/deadbeefdeadbeef")
            assert st == 404
            # malformed upload: eps wrong shape -> loud 400
            st, e = _req(srv.port, "/v1/meshes",
                         {"points": [[0.0, 0.0]], "eps": 0.1})
            assert st == 400 and "error" in e
            # oversized upload: refused on Content-Length alone, before
            # any body byte is read (bounded ingestion)
            conn = http.client.HTTPConnection("127.0.0.1", srv.port)
            try:
                conn.putrequest("POST", "/v1/meshes")
                conn.putheader("Content-Type", "application/json")
                conn.putheader("Content-Length", str((256 << 20) + 1))
                conn.endheaders()
                resp = conn.getresponse()
                assert resp.status == 400
                assert b"error" in resp.read()
            finally:
                conn.close()
            # mesh-keyed solve through the fleet
            case_body["mesh"] = mhash
            st, resp = _req(srv.port, "/v1/cases", case_body)
            assert st == 202
            st, done = _req(srv.port, f"/v1/cases/{resp['id']}?wait=1")
            assert st == 200 and done["status"] == "done"
            st, res = _req(srv.port, f"/v1/cases/{resp['id']}/result")
            assert st == 200 and res["shape"] == [120]
            got = np.array(res["values"])
            # unknown mesh -> 404; mesh + grid-field clash -> 400
            st, e = _req(srv.port, "/v1/cases",
                         dict(case_body, mesh="deadbeefdeadbeef"))
            assert st == 404
            st, e = _req(srv.port, "/v1/cases",
                         dict(case_body, shape=[120]))
            assert st == 400 and "drop" in e["error"]
            # the picked form routes through the mesh axis
            st, resp = _req(srv.port, "/v1/cases",
                            {"mesh": mhash, "k": 1.0, "T_final": 5e-4,
                             "accuracy": 1e-5, "test": True})
            assert st == 202 and resp["engine"]["method"] == "gather"
            st, done = _req(srv.port, f"/v1/cases/{resp['id']}?wait=1")
            assert done["status"] == "done"
        finally:
            srv.close()

    # bit-identity: the direct engine on the same registered mesh
    monkeypatch.setenv("NLHEAT_MESH_DIR", mdir)
    want = EnsembleEngine().run(
        [EnsembleCase(shape=(120,), nt=5, eps=0, k=1.0, dt=1e-4,
                      dh=0.0, test=True, mesh=mhash)])[0]
    assert np.array_equal(np.asarray(want), got)

    # warm boot THROUGH the router: a fresh worker process on the same
    # mesh dir + shared AOT store serves the bucket with zero programs
    # built (the test_router zero-retrace spy, now keyed by mesh hash)
    case = EnsembleCase(shape=(120,), nt=5, eps=0, k=1.0, dt=1e-4,
                        dh=0.0, test=True, mesh=mhash)
    with ReplicaRouter(replicas=1, mesh_dir=mdir,
                       program_store=sdir) as router:
        got2 = router.serve_cases([case])
        assert np.array_equal(np.asarray(want), np.asarray(got2[0]))
        metrics = router.refresh_stats()[0]["metrics"]
        assert metrics["store"]["hits"] >= 1
        assert metrics["programs_built"] == 0
