"""Adversarial / property coverage for the graph-general rebalancer.

The reference handles arbitrary locality adjacency with redistribution_dfs
+ locality_subdomain_bfs (/root/reference/src/2d_nonlocal_distributed.cpp:
706-831); its acceptance criterion is max busy-rate deviation <= 1500 of
10000 (:682-685).  These tests pin the same guarantees on
rebalance_assignment from adversarial starts the shipped fixtures never
exercise: donor islands behind neutral regions, checkerboards, random
fragmentations, heterogeneous device speeds.

Properties: (1) convergence to the reference criterion from arbitrary
starts; (2) devices that own tiles never end up empty; (3) regions that
start connected stay connected (stats reports any forced split — none may
occur on these fixtures); (4) determinism.
"""

import numpy as np
import pytest

from nonlocalheatequation_tpu.parallel.load_balance import (
    WorkTelemetry,
    _region_components,
    balance_check,
    rebalance_assignment,
)


def _iterate(assignment, telemetry, max_rounds=40, stats=None):
    """Drive rebalance rounds the way the solvers do (busy-rates from the
    current assignment feed the next pass) until balanced or the cap."""
    for _ in range(max_rounds):
        busy = telemetry.busy_rates(assignment)
        ok, _dev = balance_check(busy)
        if ok:
            return assignment, True
        assignment = rebalance_assignment(assignment, busy, stats=stats)
    return assignment, balance_check(telemetry.busy_rates(assignment))[0]


def _components_per_device(assignment, nl):
    return [_region_components(assignment, d)
            for d in range(nl) if (assignment == d).any()]


def test_checkerboard_two_devices_converges_and_defragments_nothing():
    npx = npy = 8
    a = np.fromfunction(lambda x, y: (x + y) % 2, (npx, npy), dtype=int)
    # a perfect checkerboard is already balanced for equal speeds — make it
    # unbalanced with a slow device
    tele = WorkTelemetry(2, speed_factors=np.array([1.0, 3.0]))
    out, ok = _iterate(a.copy(), tele)
    assert ok
    counts = np.bincount(out.ravel(), minlength=2)
    assert (counts > 0).all()


def test_checkerboard_four_devices_converges():
    npx = npy = 8
    a = np.fromfunction(lambda x, y: (x % 2) * 2 + (y % 2), (npx, npy),
                        dtype=int)
    tele = WorkTelemetry(4, speed_factors=np.array([1.0, 2.0, 3.0, 4.0]))
    out, ok = _iterate(a.copy(), tele)
    assert ok
    assert (np.bincount(out.ravel(), minlength=4) > 0).all()


def test_donor_island_behind_neutral_ring_cascades():
    # device 0 (donor, overloaded) sits in the center, fully enclosed by
    # device 1 (neutral ring); device 2 (receiver) owns the outer frame and
    # never touches the donor.  A boundary-grab-only balancer deadlocks
    # here; the reference's DFS cascades — ours must too.
    npx = npy = 9
    a = np.full((npx, npy), 2, dtype=np.int64)
    a[2:7, 2:7] = 1
    a[3:6, 3:6] = 0
    assert not np.any((a == 0)[:, [0, -1]]) and not np.any((a == 0)[[0, -1]])
    # single pass with explicit rates: island overloaded (donor), ring at
    # the mean (dead-band neutral), frame underloaded (receiver)
    busy = np.array([10000.0, 6000.0, 2000.0])
    assert not balance_check(busy)[0]
    stats = {}
    out = rebalance_assignment(a.copy(), busy, stats=stats)
    # the island is not adjacent to the receiver: any tile it loses must
    # have flowed through the neutral ring (2-hop chains)
    moved_from_donor = (a == 0).sum() - (out == 0).sum()
    assert moved_from_donor > 0
    assert stats["chains"] >= moved_from_donor
    # the neutral ring's count is preserved by cascading
    assert (out == 1).sum() == (a == 1).sum()
    # and full convergence under iteration with a genuinely slow island
    # (equilibrium wants the 20x-cost device down to ~2 tiles)
    out, ok = _iterate(
        a.copy(), WorkTelemetry(3, speed_factors=np.array([20.0, 1.0, 1.0])))
    assert ok


def test_random_fragmented_starts_converge(seed_count=12):
    rng = np.random.default_rng(0)
    for trial in range(seed_count):
        nl = int(rng.integers(2, 6))
        npx = int(rng.integers(4, 9))
        npy = int(rng.integers(4, 9))
        a = rng.integers(0, nl, size=(npx, npy)).astype(np.int64)
        speed = rng.uniform(0.5, 2.0, size=nl)
        tele = WorkTelemetry(nl, speed_factors=speed)
        out, ok = _iterate(a.copy(), tele)
        assert ok, f"trial {trial}: did not converge\n{a}\n->\n{out}"
        # no initially-populated device was emptied
        before = np.bincount(a.ravel(), minlength=nl)
        after = np.bincount(out.ravel(), minlength=nl)
        assert ((after > 0) | (before == 0)).all(), f"trial {trial} emptied"


def _grow_connected_partition(rng, npx, npy, nl):
    """Random CONNECTED regions via multi-source BFS growth."""
    a = np.full((npx, npy), -1, dtype=np.int64)
    seeds = rng.permutation(npx * npy)[:nl]
    frontiers = []
    for d, s in enumerate(seeds):
        x, y = divmod(int(s), npy)
        a[x, y] = d
        frontiers.append([(x, y)])
    remaining = npx * npy - nl
    while remaining:
        d = int(rng.integers(0, nl))
        if not frontiers[d]:
            continue
        x, y = frontiers[d][int(rng.integers(0, len(frontiers[d])))]
        nbrs = [(x + dx, y + dy) for dx, dy in ((1, 0), (-1, 0), (0, 1), (0, -1))
                if 0 <= x + dx < npx and 0 <= y + dy < npy
                and a[x + dx, y + dy] == -1]
        if not nbrs:
            frontiers[d].remove((x, y))
            continue
        jx, jy = nbrs[int(rng.integers(0, len(nbrs)))]
        a[jx, jy] = d
        frontiers[d].append((jx, jy))
        remaining -= 1
    return a


def test_connected_regions_stay_connected(seed_count=12):
    rng = np.random.default_rng(1)
    for trial in range(seed_count):
        nl = int(rng.integers(2, 5))
        npx = int(rng.integers(5, 10))
        npy = int(rng.integers(5, 10))
        a = _grow_connected_partition(rng, npx, npy, nl)
        assert max(_components_per_device(a, nl)) == 1
        speed = rng.uniform(0.5, 3.0, size=nl)
        tele = WorkTelemetry(nl, speed_factors=speed)
        cur = a.copy()
        for _ in range(30):
            busy = tele.busy_rates(cur)
            if balance_check(busy)[0]:
                break
            stats = {}
            cur = rebalance_assignment(cur, busy, stats=stats)
            assert stats["splits"] == 0, f"trial {trial}: forced split"
            comps = _components_per_device(cur, nl)
            assert max(comps) == 1, (
                f"trial {trial}: region fragmented\n{a}\n->\n{cur}")
        assert balance_check(tele.busy_rates(cur))[0], f"trial {trial}"


def test_determinism():
    rng = np.random.default_rng(2)
    a = rng.integers(0, 4, size=(7, 7)).astype(np.int64)
    busy = np.array([9000.0, 4000.0, 2500.0, 1200.0])
    out1 = rebalance_assignment(a.copy(), busy)
    out2 = rebalance_assignment(a.copy(), busy)
    assert (out1 == out2).all()


def test_single_tile_donors_never_emptied():
    # every donor owns exactly one tile: nothing can move, but the pass
    # must terminate cleanly and keep everyone populated
    a = np.arange(4, dtype=np.int64).reshape(2, 2)
    busy = np.array([10000.0, 9000.0, 500.0, 400.0])
    out = rebalance_assignment(a.copy(), busy)
    assert (np.bincount(out.ravel(), minlength=4) > 0).all()


def test_reference_fixture_shapes_still_converge():
    # the shipped 25s/2n map: 24 of 25 tiles on locality 1 (the reference's
    # own deliberately-imbalanced manual fixture, README.md:69-72)
    a = np.ones((5, 5), dtype=np.int64)
    a[0, 0] = 0
    tele = WorkTelemetry(2)
    out, ok = _iterate(a.copy(), tele)
    assert ok
    counts = np.bincount(out.ravel(), minlength=2)
    assert abs(counts[0] - counts[1]) <= 1


@pytest.mark.parametrize("nl,n", [(2, 21), (3, 21), (5, 20), (7, 21)])
def test_long_strip_grid(nl, n):
    # degenerate 1xN geometry: regions are intervals; transfers must flow
    # along the line through every intermediate.  n chosen so an integer
    # split can actually meet the <=1500 criterion (21 tiles over 5 devices
    # bottoms out at 1600 under the lockstep busy model — infeasible)
    a = np.zeros((1, n), dtype=np.int64)
    # all tiles on the last device
    a[:] = nl - 1
    for d in range(nl - 1):
        a[0, d] = d
    tele = WorkTelemetry(nl)
    out, ok = _iterate(a.copy(), tele, max_rounds=60)
    assert ok
    assert (np.bincount(out.ravel(), minlength=nl) > 0).all()
    assert max(_components_per_device(out, nl)) == 1


def test_single_tile_neutral_intermediate_does_not_deadlock():
    # reviewer repro: receiver | single-tile dead-band neutral | donor on a
    # 1x5 strip.  Receiver-end-first chain execution emptied the neutral
    # before it could grab its replacement and silently gave up; the
    # donor-first order must move work through it
    a = np.array([[0, 1, 2, 2, 2]], dtype=np.int64)
    busy = np.array([1000.0, 5000.0, 9000.0])
    stats = {}
    out = rebalance_assignment(a.copy(), busy, stats=stats)
    assert stats["chains"] > 0
    assert (out != a).any()
    # the neutral's count is preserved, the donor shrank, receiver grew
    assert (out == 1).sum() == 1
    assert (out == 2).sum() < (a == 2).sum()
    assert (out == 0).sum() > 1
    # nobody emptied
    assert (np.bincount(out.ravel(), minlength=3) > 0).all()
