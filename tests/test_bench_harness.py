"""The bench harness must be impossible to zero out (VERDICT r2 #1).

Round 1 crashed the bench; round 2's hung TPU init converted 480s into a
single 0.0.  These tests drive bench.py as a black box on CPU and assert the
recovery ladder: a healthy run measures, a mid-ladder deadline emits the best
completed rung as partial, and a hung method probe is killed and retried with
the sat path forced.  Reference contract: a check that cannot run is a failed
check, not a missing one (CMakeLists.txt:101-154).
"""

import glob
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def run_bench(env_extra, timeout=240):
    env = dict(os.environ)
    env.pop("BENCH_FAULT", None)
    env.pop("BENCH_METHOD", None)
    env.pop("BENCH_ACCURACY", None)
    env.update({"BENCH_PLATFORM": "cpu", "BENCH_GRID": "128", "BENCH_STEPS": "3",
                "BENCH_LADDER": "64"}, **env_extra)
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True, env=env,
        timeout=timeout,
    )
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert lines, f"no stdout JSON; stderr tail: {proc.stderr[-800:]}"
    return proc, json.loads(lines[-1])


def test_healthy_run_measures_full_ladder():
    proc, rec = run_bench({})
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["grid"] == 128
    assert rec["partial"] is False
    assert rec["method"] == "sat"  # non-TPU backend
    assert rec["accuracy"]["ok"] is True


def test_non_tpu_line_carries_banked_tpu_evidence():
    # when the run cannot reach the TPU, the line must point at the
    # newest runner-promoted on-device artifact, clearly labeled as not
    # from this run (repo ships BENCH_live_r4-20260802-*.json).  The
    # skip guard applies bench.py's own qualification (parseable,
    # backend=tpu, value>0): a rotten-only artifact set is a documented
    # no-evidence case, not a test failure
    def _qualifies(p):
        try:
            with open(p) as f:
                rec = json.load(f)
            return rec.get("backend") == "tpu" and rec.get("value", 0) > 0
        except Exception:
            return False

    banked = [p for p in glob.glob(os.path.join(
        REPO, "docs", "bench", "BENCH_live_r*-*.json")) if _qualifies(p)]
    if not banked:
        pytest.skip("no qualifying promoted on-TPU artifact in the repo")
    proc, rec = run_bench({})
    assert proc.returncode == 0
    assert rec["backend"] == "cpu"
    ev = rec["banked_tpu_evidence"]
    assert ev["value"] > 0
    assert ev["source"].startswith("docs/bench/BENCH_live_r")
    assert "NOT from this run" in ev["note"]
    # the banked block must never displace this run's own measurement
    assert rec["value"] > 0 and rec["value"] != ev["value"]


def test_accuracy_optout_skips_gate_but_still_measures():
    # the opportunistic runner's window gate sets BENCH_ACCURACY=0 (the
    # f64 oracle pass costs ~2 min per gate on the real tunnel); the
    # measurement itself must be unaffected and the artifact must simply
    # carry no accuracy block rather than a fake one
    proc, rec = run_bench({"BENCH_ACCURACY": "0"})
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["partial"] is False
    assert "accuracy" not in rec
    assert "accuracy gate skipped" in proc.stderr + proc.stdout


def test_bench_ensemble_mode_emits_cases_field():
    # BENCH_ENSEMBLE=B: each rung advances B same-shape cases as one
    # batched program; the JSON line must carry the case count and the
    # aggregate cases*points*steps/s field on the same one-line rc=0
    # contract — here exercised on the CPU fallback ladder
    proc, rec = run_bench({"BENCH_ENSEMBLE": "4"})
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["cases"] == 4
    assert rec["variant"] == "ensemble4"
    assert rec["cases*points*steps/s"] == rec["value"]
    assert rec["partial"] is False
    assert rec["accuracy"]["ok"] is True  # the solo gate still runs


def test_bench_tta_mode_emits_steps_to_solution():
    # BENCH_TTA=1: the time-to-accuracy rung — euler vs rkc vs expo to a
    # fixed (grid, T_final, error target); the JSON must carry the
    # variant label, the winning stepper, its effective dt/steps, the
    # steps-to-solution ratio, and the per-arm breakdown — on the same
    # one-line rc=0 ladder
    proc, rec = run_bench({"BENCH_TTA": "1", "BENCH_GRID": "64",
                           "BENCH_LADDER": "64", "BENCH_STEPS": "20",
                           "BENCH_ACCURACY": "0"})
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["variant"] == "tta"
    assert rec["stepper"] in ("euler", "rkc", "expo")
    assert rec["steps_taken"] >= 1 and rec["eff_dt"] > 0
    assert rec["steps_ratio"] >= 1.0
    arms = rec["tta"]
    assert set(arms) == {"euler", "rkc", "expo"}
    for arm in arms.values():
        assert arm["steps"] >= 1 and "err_l2_per_n" in arm
    assert arms["expo"]["method"] == "fft"
    # the winner's record backs the headline fields
    assert arms[rec["stepper"]]["steps"] == rec["steps_taken"]


def test_bench_warmboot_mode_emits_cold_warm_ab(tmp_path):
    # BENCH_WARMBOOT=1: the cold-vs-warm boot A/B over one shared AOT
    # program store dir (ISSUE 9, serve/program_store.py).  The JSON
    # must carry the warmboot variant, both first-chunk walls, the
    # cold/warm speedup, a counted store hit (the warm arm must LOAD,
    # not recompile), and the bit-identity flag — on the same one-line
    # rc=0 ladder
    store = tmp_path / "store"
    proc, rec = run_bench({"BENCH_WARMBOOT": "1", "BENCH_GRID": "48",
                           "BENCH_LADDER": "48", "BENCH_ACCURACY": "0",
                           "BENCH_WARMBOOT_DIR": str(store)})
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["variant"] == "warmboot"
    assert rec["cold_first_chunk_s"] > 0
    assert rec["warm_first_chunk_s"] > 0
    assert rec["warmboot_speedup"] == pytest.approx(
        rec["cold_first_chunk_s"] / rec["warm_first_chunk_s"], rel=1e-2)
    assert rec["store_hits"] == 1
    assert rec["store_misses"] == 1
    assert rec["bit_identical"] is True
    # the shared dir holds the serialized executable for the next boot
    assert list(store.glob("*.aotprog"))
    # a second run against the SAME dir: the populate arm now hits too
    # (misses 0) and the gate evidence still banks
    proc2, rec2 = run_bench({"BENCH_WARMBOOT": "1", "BENCH_GRID": "48",
                             "BENCH_LADDER": "48", "BENCH_ACCURACY": "0",
                             "BENCH_WARMBOOT_DIR": str(store)})
    assert proc2.returncode == 0
    assert rec2["store_hits"] == 1
    assert rec2["store_misses"] == 0
    assert rec2["warmboot_speedup"] > 0


def test_bench_router_mode_emits_fleet_ab(tmp_path):
    # BENCH_ROUTER=N: the replica-fleet A/B (ISSUE 10, serve/router.py
    # + serve/http.py) — 1-replica vs N-replica walls over one shared
    # store dir plus the offered-load sweep through the admission gate.
    # The JSON must carry the router variant, the speedup, throughput,
    # accept/shed counts, the latency percentiles, the sweep, and the
    # bit-identity flag — on the same one-line rc=0 ladder.  Tiny grids
    # are submit-bound (the 2.5x scale-out acceptance is the calibrated
    # 256^2+ proxy in docs/round12.md), so this asserts the STRUCTURE,
    # not the ratio.
    store = tmp_path / "store"
    proc, rec = run_bench({"BENCH_ROUTER": "2", "BENCH_GRID": "48",
                           "BENCH_LADDER": "48", "BENCH_ACCURACY": "0",
                           "BENCH_ROUTER_STEPS": "60",
                           "BENCH_ROUTER_CASES": "6",
                           "BENCH_ROUTER_DIR": str(store)},
                          timeout=420)
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["variant"] == "router2"
    assert rec["replicas"] == 2
    assert rec["cases"] == 6
    assert rec["router_speedup"] > 0
    assert rec["throughput_cases_s"] > 0
    assert rec["bit_identical"] is True
    assert set(rec["load_sweep"]) == {"x2", "burst"}
    for point in rec["load_sweep"].values():
        assert point["offered"] == 12
        assert point["accepted"] + point["shed"] == point["offered"]
        assert point["max_pending"] <= 4  # the admission bound (2*N)
    assert {"p50", "p99", "unloaded_p99"} <= set(rec["latency_ms"])
    # the fleet arms shared ONE store dir: the single-replica arm
    # populated it, so the dir holds serialized executables
    assert list(store.glob("*.aotprog"))


def test_bench_trace_fleet_mode_emits_merged_timeline(tmp_path):
    # BENCH_TRACE_FLEET (with BENCH_ROUTER=N): the fleet observability
    # A/B (ISSUE 11) — traced vs untraced N-replica fleets over one
    # shared store dir.  The JSON must carry the routerobs variant, the
    # overhead ratio, the fleet span count, the merged-trace path (a
    # Perfetto-loadable document spanning the router AND the replicas),
    # the retrace-watchdog verdict (0 steady-state builds: the warm
    # pass left every program resident/stored), and bit-identity — on
    # the same one-line rc=0 ladder.
    import json

    store = tmp_path / "store"
    tdir = tmp_path / "fleet_trace"
    proc, rec = run_bench({"BENCH_ROUTER": "2", "BENCH_GRID": "48",
                           "BENCH_LADDER": "48", "BENCH_ACCURACY": "0",
                           "BENCH_ROUTER_STEPS": "60",
                           "BENCH_ROUTER_CASES": "6",
                           "BENCH_ROUTER_DIR": str(store),
                           "BENCH_TRACE_FLEET": str(tdir)},
                          timeout=420)
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["variant"] == "routerobs2"
    assert rec["replicas"] == 2 and rec["cases"] == 6
    assert rec["trace_overhead"] > 0
    assert rec["spans_total"] > 0
    assert rec["steady_state_builds"] == 0
    assert rec["bit_identical"] is True
    # router + 2 replicas in the merged timeline, flows intact
    assert rec["merged_processes"] == 3
    doc = json.loads(open(rec["merged_trace_path"]).read())
    events = doc["traceEvents"]
    assert len({e.get("pid") for e in events
                if e.get("ph") != "M"}) == 3
    assert any(e["ph"] in ("s", "t", "f") for e in events)


def test_bench_fleet_tcp_mode_emits_transport_ab(tmp_path):
    # BENCH_FLEET_TCP=N: the worker-transport A/B + sharded big-case
    # tier (ISSUE 12, serve/transport.py + serve/router.py
    # fleet_tcp_ab) — pipe vs loopback-TCP walls over one shared store
    # dir, then the mixed small+sharded sweep on a TCP fleet with the
    # gang replica up.  The JSON must carry the fleettcp variant, the
    # transport label, the tcp_overhead ratio, the sharded-case
    # accounting (comm + mesh evidence), accept/shed counts, and the
    # bit-identity flag (pipe == tcp AND gang == offline distributed) —
    # on the same one-line rc=0 ladder.  Tiny grids are submit-bound:
    # this asserts STRUCTURE, not the overhead ratio.
    store = tmp_path / "store"
    proc, rec = run_bench({"BENCH_FLEET_TCP": "2", "BENCH_GRID": "48",
                           "BENCH_LADDER": "48", "BENCH_ACCURACY": "0",
                           "BENCH_ROUTER_STEPS": "60",
                           "BENCH_FLEET_CASES": "6",
                           "BENCH_FLEET_SHARDED": "1",
                           "BENCH_FLEET_GANG": "2",
                           "BENCH_ROUTER_DIR": str(store)},
                          timeout=420)
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["variant"] == "fleettcp2"
    assert rec["transport"] == "tcp"
    assert rec["replicas"] == 2 and rec["cases"] == 6
    assert rec["tcp_overhead"] > 0
    assert rec["router_speedup"] > 0  # the 1-replica TCP arm ran
    # the warm pass dispatched the sharded case to the gang replica,
    # and the sweep re-offered it (paced + burst)
    assert rec["sharded_cases"] >= 1
    assert rec["sharded"]["grid"] == 96
    assert rec["sharded"]["threshold"] == 48 * 48
    assert rec["sharded"]["comm"] in ("fused", "collective")
    assert rec["sharded"]["devices"] == 2
    assert rec["bit_identical"] is True
    assert set(rec["load_sweep"]) == {"x2", "burst"}
    for point in rec["load_sweep"].values():
        assert point["accepted"] + point["shed"] == point["offered"]
        assert point["max_pending"] <= 4  # the admission bound (2*N)
    # both transport arms shared ONE store dir (the pipe arm populated
    # it, the TCP arm warm-booted)
    assert list(store.glob("*.aotprog"))


def test_bench_tta_fleet_mode_emits_picker_evidence():
    # BENCH_TTA_FLEET=1: the fleet time-to-accuracy + engine-picker
    # rung (ISSUE 13, parallel/stepper_halo.py + serve/picker.py) — the
    # same fixed sharded problem served euler-named vs picker-chosen
    # through a 1-replica + gang fleet, plus the small-tier mixed
    # sweep.  eps 2 at 32^2 puts the accuracy-capped dt well past the
    # Euler bound, so the picker genuinely picks rkc and the JSON must
    # carry the ttafleet variant, the >= 10x steps_ratio, the picked
    # engine label, met_target (the picker's accuracy promise,
    # MEASURED) and the gang bit-identity — on the one-line rc=0 ladder
    proc, rec = run_bench({"BENCH_TTA_FLEET": "1", "BENCH_GRID": "32",
                           "BENCH_LADDER": "32", "BENCH_EPS": "2",
                           "BENCH_STEPS": "20", "BENCH_ACCURACY": "0",
                           "BENCH_FLEET_GANG": "2"}, timeout=420)
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["variant"] == "ttafleet"
    assert rec["stepper"] == "rkc" and rec["stages"] >= 2
    assert rec["picker_engine"].startswith("rkc[")
    assert rec["steps_ratio"] >= 10
    assert rec["steps_taken"] * rec["steps_ratio"] == rec["steps"]
    assert rec["tta_speedup"] > 0
    assert rec["met_target"] is True
    assert rec["bit_identical"] is True
    assert rec["picker_speedup"] > 0  # the mixed sweep ran both arms
    assert rec["sharded"]["stepper"] == "rkc"
    assert rec["sharded"]["devices"] == 2
    assert rec["sharded"]["threshold"] == 32 * 32 // 2


def test_bench_scrubs_leaked_program_store():
    # a store dir leaked from a developer shell must not silently
    # warm-boot a headline measurement's compiles
    proc, rec = run_bench({"NLHEAT_PROGRAM_STORE": "/tmp/leaked-store",
                           "BENCH_ACCURACY": "0"})
    assert proc.returncode == 0
    assert "scrubbed leaked NLHEAT_PROGRAM_STORE" in proc.stderr
    assert rec["value"] > 0  # the measurement itself is unaffected


def test_bench_scrubs_leaked_picker_knobs():
    # a leaked picker ladder / expo opt-in would silently reroute the
    # ttafleet rung's engine choice (ISSUE 13) — the same honesty scrub
    # as the store knob above
    proc, rec = run_bench({"NLHEAT_PICK_STAGES": "2",
                           "NLHEAT_PICK_EXPO": "1",
                           "BENCH_ACCURACY": "0"})
    assert proc.returncode == 0
    assert "scrubbed leaked NLHEAT_PICK_STAGES" in proc.stderr
    assert "scrubbed leaked NLHEAT_PICK_EXPO" in proc.stderr
    assert rec["value"] > 0


def test_bench_multichip_mode_emits_halo_overlap():
    # BENCH_MULTICHIP=N: the sharded-solving A/B — the distributed 2D
    # solver over one shared N-device mesh, collective vs FUSED halo
    # engines (ops/pallas_halo.py).  The JSON line must carry the
    # multichipN variant, comm=fused, the collective/fused halo_overlap
    # ratio, and the mesh layout, on the same one-line rc=0 contract —
    # here on the CPU proxy where the parent forces N virtual devices
    proc, rec = run_bench({"BENCH_MULTICHIP": "8", "BENCH_GRID": "64",
                           "BENCH_LADDER": "64", "BENCH_ACCURACY": "0"})
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["variant"] == "multichip8"
    assert rec["comm"] == "fused"
    assert rec["halo_overlap"] > 0
    assert rec["devices"] == 8
    assert rec["mesh"] == {"x": 4, "y": 2}
    assert rec["method"] == "pallas"  # both A/B arms run the pallas path
    assert rec["partial"] is False


def test_bench_serve_mode_emits_amortization_and_latency():
    # BENCH_SERVE=D: the serving-pipeline A/B — fenced (depth 1) vs
    # pipelined (depth D) schedules of C single-case chunks in one rung.
    # The JSON line must carry the serveD variant label, the case count,
    # the fenced/pipelined fence_amortization ratio, per-request latency
    # percentiles, and the measured occupancy, on the same one-line rc=0
    # contract — here exercised on the CPU fallback ladder
    proc, rec = run_bench({"BENCH_SERVE": "3", "BENCH_ACCURACY": "0"})
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["variant"] == "serve3"
    assert rec["cases"] == 8
    assert rec["fence_amortization"] > 0
    assert {"p50", "p90", "p99"} <= set(rec["latency_ms"])
    # the pipelined half genuinely overlapped: depth was reached
    assert rec["occupancy"]["max"] == 3
    assert rec["partial"] is False


def test_bench_trace_mode_emits_overhead_and_artifact(tmp_path):
    # BENCH_TRACE (with BENCH_SERVE=D): the observability A/B — the same
    # pipelined schedule timed with the obs/ span tracer off vs
    # installed.  The JSON line must carry the serveobsD variant label,
    # the traced/untraced overhead ratio, the lifetime span count, and
    # (BENCH_TRACE=DIR) the path of a written Perfetto-loadable
    # host_trace.json, on the same one-line rc=0 contract
    import json

    tdir = tmp_path / "trace"
    proc, rec = run_bench({"BENCH_SERVE": "3", "BENCH_TRACE": str(tdir),
                           "BENCH_ACCURACY": "0"})
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["variant"] == "serveobs3"
    assert rec["cases"] == 8
    assert rec["trace_overhead"] > 0
    assert rec["spans"] > 0
    doc = json.loads(open(rec["trace_path"]).read())
    assert len(doc["traceEvents"]) == rec["spans"]
    assert {"serve.build", "serve.dispatch", "serve.fetch"} <= {
        ev["name"] for ev in doc["traceEvents"]}
    assert rec["partial"] is False


def test_bench_servefault_mode_serves_through_injected_fault():
    # BENCH_SERVE_FAULTS: the chaos rung — the pipelined schedule runs
    # once under a deterministic injected plan through the supervised
    # pipeline (retries + first-failure breaker + CPU fallback).  The
    # plan fails one dispatch attempt AND its first retry, so the
    # breaker demonstrably opens and the fallback route serves; the JSON
    # line must show every request served (no poison), at least one
    # fallback chunk, and the servefault variant label, on the same
    # one-line rc=0 contract
    proc, rec = run_bench({"BENCH_SERVE": "3",
                           "BENCH_SERVE_FAULTS": "raise@1x2",
                           "BENCH_ACCURACY": "0"})
    assert proc.returncode == 0
    assert rec["value"] > 0
    assert rec["variant"] == "servefault3"
    assert rec["cases"] == 8
    assert rec["served"] == 8 and rec["poison"] == 0
    assert rec["fallback_chunks"] >= 1
    assert rec["retries_total"] >= 1
    assert rec["breaker_transitions"] >= 1  # closed -> open observed
    assert rec["fault_plan"] == "raise@1x2"
    assert rec["partial"] is False


def test_leaked_fault_plan_scrubbed_from_headline_run():
    # an ambient NLHEAT_FAULT_PLAN (leaked from a chaos shell) must not
    # inject failures into a normal measurement: the parent scrubs it
    # and the run completes as a plain healthy ladder
    proc, rec = run_bench({"NLHEAT_FAULT_PLAN": "raise@0x*",
                           "BENCH_ACCURACY": "0"})
    assert proc.returncode == 0
    assert rec["value"] > 0 and rec["partial"] is False
    assert "variant" not in rec
    assert "scrubbed leaked NLHEAT_FAULT_PLAN" in proc.stderr


def test_tight_deadline_emits_partial_not_zero():
    # Budget long enough for probe + first rung, short enough to cut the
    # ladder; grid 512 on CPU forces a multi-second second rung.
    proc, rec = run_bench(
        {"BENCH_GRID": "512", "BENCH_LADDER": "64", "BENCH_STEPS": "3",
         "BENCH_WATCHDOG_S": "40"},
        timeout=120,
    )
    assert rec["value"] > 0, f"tight deadline zeroed the bench: {rec}"
    assert rec["grid"] in (64, 512)
    if rec["grid"] == 64:
        assert rec["partial"] is True
        assert proc.returncode == 0  # a partial result is a result


def test_hung_method_probe_is_killed_and_retried_with_sat():
    proc, rec = run_bench(
        {"BENCH_FAULT": "hang_method",
         "BENCH_METHOD_TIMEOUT_S": "8", "BENCH_PROBE_TIMEOUT_S": "30",
         "BENCH_WATCHDOG_S": "120"},
        timeout=180,
    )
    # With BENCH_METHOD unset the child enters the faulted probe and hangs;
    # the parent must kill it and re-run with method=sat forced (which
    # bypasses the fault), landing a real measurement.
    assert rec["value"] > 0, f"hung child zeroed the bench: {rec}"
    assert rec["method"] == "sat"
    assert proc.returncode == 0


def test_first_rung_always_attempted_even_late():
    # A child budget that is nearly spent must still try the first rung
    # (degrade the result, never zero it).  The squeeze is INJECTED — a
    # test-mode fault pins the child budget to 5s under a generous real
    # watchdog — instead of racing a tight watchdog against host load
    # (the old 25s/40s schedules both flaked under parallel suite runs;
    # VERDICT r4 #7)
    proc, rec = run_bench({
        "BENCH_WATCHDOG_S": "240",
        "BENCH_TEST_MODE": "1",
        "BENCH_FAULT": "tiny_child_budget",
        "BENCH_FAULT_BUDGET_S": "5",
    }, timeout=300)
    assert rec["value"] > 0, f"late start zeroed the bench: {rec}"
    assert rec["grid"] == 64 and rec["partial"] is True, rec
    assert "skipping rung" in proc.stderr  # the squeeze genuinely engaged


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))


@pytest.mark.slow  # ~60 s: a full tpu_refresh.sh gate run.  Marked slow
# (PR 2) to hold the 870 s tier-1 budget — the refresh runbook is the
# LEGACY known-healthy-chip path (tools/tpu_opportunistic.sh is the live
# runner, policy-tested in tier-1); run `pytest -m slow` for this one.
def test_tpu_refresh_aborts_on_unhealthy_backend(tmp_path):
    """The refresh runbook must gate the unprotected measurement tools on
    bench.py's hang-proof probe: a CPU-fallback artifact aborts the run."""
    import subprocess

    # log + table routed into tmp_path: the docs/bench/ evidence directory
    # must never be touched by tests (a blanket refresh-*.log cleanup here
    # destroyed a real measurement log on 2026-07-30)
    env = dict(os.environ, BENCH_PLATFORM="cpu", BENCH_WATCHDOG_S="240",
               BENCH_STEPS="3",
               BENCH_REFRESH_OUT=str(tmp_path / "refresh.log"),
               BENCH_REFRESH_TABLE=str(tmp_path / "table.jsonl"))
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "tools", "tpu_refresh.sh")],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO,
    )
    assert proc.returncode == 1
    assert "ABORT: bench did not reach the TPU backend" in proc.stdout
    assert (tmp_path / "refresh.log").exists()


def test_probe_retries_through_fast_failures(tmp_path):
    """A resetting tunnel fails probes FAST (UNAVAILABLE); the probe phase
    must keep retrying cheap failures instead of giving up after 3 — five
    injected fast failures then success must still land on the (cpu test)
    backend WITHOUT the cpu_fallback degradation label."""
    counter = str(tmp_path / "flaky")
    proc, rec = run_bench(timeout=360, env_extra={
        "BENCH_PLATFORM": "cpu",
        "BENCH_WATCHDOG_S": "240",
        "BENCH_STEPS": "3",
        "BENCH_LADDER": "64",
        "BENCH_GRID": "64",
        "BENCH_FAULT": "probe_flaky",
        "BENCH_FAULT_FILE": counter,
        "BENCH_FAULT_N": "5",
    })
    assert rec["value"] > 0
    assert "cpu_fallback" not in rec, rec
    assert int(open(counter).read()) == 5  # all five injected failures hit
    assert proc.stderr.count("probe attempt failed") >= 5


def test_late_heal_retry_replaces_cpu_fallback(tmp_path):
    """The wedge cycle often heals mid-watchdog: after the CPU fallback
    ladder completes with budget to spare, one more TPU probe runs, and a
    successful re-measure replaces the fallback headline (labeled
    cpu_fallback="recovered-late").  The heal moment is EVENT-driven (the
    test touches BENCH_FAULT_FILE the moment bench reports the fallback),
    so the fallback is guaranteed to run first and no wall-clock schedule
    can race host load — the old T0+80s anchor flaked under parallel
    suite runs (VERDICT r4 #7)."""
    import threading

    heal = tmp_path / "healed"
    env = dict(os.environ)
    for k in ("BENCH_FAULT", "BENCH_METHOD", "BENCH_PLATFORM"):
        env.pop(k, None)
    env.update({
        "BENCH_GRID": "64", "BENCH_LADDER": "64", "BENCH_STEPS": "3",
        # generous watchdog: the run ends long before it fires; the probe
        # phase is pinned short so pre-fallback fast-fails don't burn the
        # default 45% of the budget
        "BENCH_WATCHDOG_S": "170",
        "BENCH_PROBE_PHASE_S": "8",
        "BENCH_PROBE_TIMEOUT_S": "20",
        "BENCH_LATE_RETRY_S": "5",
        "BENCH_TEST_MODE": "1",
        "BENCH_FAULT": "probe_heal_after",
        "BENCH_FAULT_FILE": str(heal),
    })
    proc = subprocess.Popen(
        [sys.executable, BENCH], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, env=env,
    )
    stderr_lines = []
    stdout_chunks = []

    def watch():
        for line in proc.stderr:
            stderr_lines.append(line)
            if "falling back to CPU" in line and not heal.exists():
                heal.write_text("1")

    # both pipes drain on daemon threads so the timeout gate below is the
    # real ceiling — a bench regression that hangs before its watchdog
    # starts must fail this test at 280s, not block the suite forever
    t = threading.Thread(target=watch, daemon=True)
    t2 = threading.Thread(
        target=lambda: stdout_chunks.append(proc.stdout.read()), daemon=True)
    t.start()
    t2.start()
    try:
        rc = proc.wait(timeout=280)
    finally:
        proc.kill()
    t.join(timeout=10)
    t2.join(timeout=10)
    out = "".join(stdout_chunks)
    stderr = "".join(stderr_lines)
    lines = [ln for ln in out.strip().splitlines() if ln.strip()]
    assert lines, f"no stdout JSON; stderr tail: {stderr[-800:]}"
    rec = json.loads(lines[-1])
    assert rec["value"] > 0, f"late-heal run zeroed the bench: {rec}"
    assert rec.get("cpu_fallback") == "recovered-late", rec
    assert "late-probe ok" in stderr
    assert rc == 0

def test_malformed_baseline_value_does_not_void_the_line(tmp_path):
    # the one-JSON-line contract must survive a JSON-valid baseline whose
    # VALUE is unusable (string, zero) — the division lives outside the
    # file-read try, so it needs its own guard (round-4 review finding)
    for bad in ('{"points_steps_per_sec": "fast"}',
                '{"points_steps_per_sec": 0}',
                '[1, 2]'):  # valid JSON, not an object
        p = tmp_path / "baseline.json"
        p.write_text(bad)
        proc, rec = run_bench({"BENCH_BASELINE_PATH": str(p)})
        assert proc.returncode == 0
        assert rec["value"] > 0
        assert rec["vs_baseline"] == 0.0


def test_baseline_basis_label_flows_into_the_emitted_line(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text('{"points_steps_per_sec": 1000.0, "basis": "per-core"}')
    proc, rec = run_bench({"BENCH_BASELINE_PATH": str(p)})
    assert proc.returncode == 0
    assert rec["vs_baseline"] > 0
    assert rec["vs_baseline_basis"] == "per-core"
