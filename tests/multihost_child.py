"""One controller of the two-process loopback solve.

Run by tests/test_multihost.py (not collected by pytest — no test_ prefix):
``python multihost_child.py <coordinator> <num_processes> <process_id>``.
Each process owns 2 virtual CPU devices (XLA_FLAGS set by the parent); the
2x2 mesh therefore SPANS the process boundary, so the shard_map halo
exchange rides the cross-process (gloo) transport — the DCN analog of the
reference's multi-locality parcelport (src/2d_nonlocal_distributed.cpp's
get_data RPCs under srun -n N).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

from nonlocalheatequation_tpu.parallel import multihost  # noqa: E402

assert multihost.init_from_env(coord, nproc, pid), "explicit init must run"
assert jax.process_count() == nproc
assert len(jax.devices()) == 2 * nproc, "expected 2 local devices per process"

from nonlocalheatequation_tpu.models.solver2d import Solver2D  # noqa: E402
from nonlocalheatequation_tpu.parallel.distributed2d import (  # noqa: E402
    Solver2DDistributed,
)
from nonlocalheatequation_tpu.parallel.mesh import make_mesh  # noqa: E402

# shard edge 8: eps=3 = one-hop band exchange, eps=9 = multi-hop ring (the
# long-horizon path), both now crossing the process boundary
for eps in (3, 9):
    mesh = make_mesh(2, 2)
    d = Solver2DDistributed(16, 16, 1, 1, nt=3, eps=eps, k=1.0, dt=1e-4,
                            dh=1.0 / 16, mesh=mesh)
    d.test_init()
    ud = d.do_work()
    multihost.assert_same_on_all_hosts(ud, f"solution eps={eps}")
    o = Solver2D(16, 16, 3, eps=eps, k=1.0, dt=1e-4, dh=1.0 / 16,
                 backend="oracle")
    o.test_init()
    err = float(np.abs(ud - o.do_work()).max())
    assert err < 1e-12, f"eps={eps}: deviates from serial oracle by {err:.3e}"
    print(f"MH-OK p{pid} eps={eps} err={err:.2e}", flush=True)
