"""One controller of the two-process loopback solve.

Run by tests/test_multihost.py (not collected by pytest — no test_ prefix):
``python multihost_child.py <coordinator> <num_processes> <process_id>``.
Each process owns 2 virtual CPU devices (XLA_FLAGS set by the parent); the
meshes therefore SPAN the process boundary, so the shard_map halo exchange
rides the cross-process (gloo) transport — the DCN analog of the
reference's multi-locality parcelport (src/2d_nonlocal_distributed.cpp's
get_data RPCs under srun -n N).

Legs: 2D 16x16 on a 2x2 mesh at eps=3 (one-hop halo) and eps=9 (multi-hop
ring); 3D 8^3 on a (2,2,1) mesh at eps=2 (one-hop) and eps=5 (multi-hop).
Each leg asserts cross-host determinism and <=1e-12 agreement with the
serial oracle, and prints one ``MH-OK p<pid> ...`` line the parent test
greps for.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

from nonlocalheatequation_tpu.parallel import multihost  # noqa: E402

assert multihost.init_from_env(coord, nproc, pid), "explicit init must run"
assert jax.process_count() == nproc
assert len(jax.devices()) == 2 * nproc, "expected 2 local devices per process"

from nonlocalheatequation_tpu.models.solver2d import Solver2D  # noqa: E402
from nonlocalheatequation_tpu.parallel.distributed2d import (  # noqa: E402
    Solver2DDistributed,
)
from nonlocalheatequation_tpu.parallel.mesh import make_mesh  # noqa: E402

# shard edge 8: eps=3 = one-hop band exchange, eps=9 = multi-hop ring (the
# long-horizon path), both now crossing the process boundary
for eps in (3, 9):
    mesh = make_mesh(2, 2)
    d = Solver2DDistributed(16, 16, 1, 1, nt=3, eps=eps, k=1.0, dt=1e-4,
                            dh=1.0 / 16, mesh=mesh)
    d.test_init()
    ud = d.do_work()
    multihost.assert_same_on_all_hosts(ud, f"solution eps={eps}")
    o = Solver2D(16, 16, 3, eps=eps, k=1.0, dt=1e-4, dh=1.0 / 16,
                 backend="oracle")
    o.test_init()
    uo = o.do_work()
    err = float(np.abs(ud - uo).max())
    assert err < 1e-12, f"eps={eps}: deviates from serial oracle by {err:.3e}"
    print(f"MH-OK p{pid} eps={eps} err={err:.2e}", flush=True)
    if eps == 3:
        # communication-avoiding superstep across the PROCESS boundary: one
        # K*eps-wide exchange per K steps over the gloo transport (the DCN
        # analog — the latency-bound regime the schedule exists for)
        ds = Solver2DDistributed(16, 16, 1, 1, nt=3, eps=eps, k=1.0,
                                 dt=1e-4, dh=1.0 / 16, mesh=make_mesh(2, 2),
                                 superstep=2)
        ds.test_init()
        us = ds.do_work()
        multihost.assert_same_on_all_hosts(us, "superstep solution")
        errs = float(np.abs(us - uo).max())
        assert errs < 1e-12, f"superstep deviates by {errs:.3e}"
        print(f"MH-OK p{pid} superstep err={errs:.2e}", flush=True)

# 3D over a (2, 2, 1) mesh — same cross-process halo, one more axis:
# eps=2 is the one-hop band exchange, eps=5 > shard edge 4 the multi-hop
# ring, mirroring the 2D pair above
from nonlocalheatequation_tpu.models.solver3d import Solver3D  # noqa: E402
from nonlocalheatequation_tpu.parallel.distributed3d import (  # noqa: E402
    Solver3DDistributed,
)
from nonlocalheatequation_tpu.parallel.mesh import make_mesh_3d  # noqa: E402

for eps3 in (2, 5):
    mesh3 = make_mesh_3d(2, 2, 1)
    d3 = Solver3DDistributed(8, 8, 8, nt=2, eps=eps3, k=1.0, dt=1e-4,
                             dh=0.05, mesh=mesh3)
    d3.test_init()
    u3 = d3.do_work()
    multihost.assert_same_on_all_hosts(u3, f"3d solution eps={eps3}")
    o3 = Solver3D(8, 8, 8, 2, eps=eps3, k=1.0, dt=1e-4, dh=0.05,
                  backend="oracle")
    o3.test_init()
    err3 = float(np.abs(u3 - o3.do_work()).max())
    assert err3 < 1e-12, (
        f"3d eps={eps3}: deviates from serial oracle by {err3:.3e}")
    print(f"MH-OK p{pid} 3d eps={eps3} err={err3:.2e}", flush=True)

# unstructured offsets (DIA) over the process-spanning 1D mesh: per-shard
# diagonal weights + ppermute halo bands crossing the gloo transport — the
# gather-free multichip unstructured path, multi-controller.  Both
# processes build the identical op (same seed: the init contract).
from jax.sharding import NamedSharding, PartitionSpec  # noqa: E402

from nonlocalheatequation_tpu.ops.unstructured import (  # noqa: E402
    ShardedUnstructuredOp,
    UnstructuredNonlocalOp,
)

rng = np.random.default_rng(0)
m = 32
h = 1.0 / m
gx, gy = np.meshgrid(np.arange(m) * h, np.arange(m) * h, indexing="ij")
pts = np.stack([gx.ravel(), gy.ravel()], axis=1)
pts += rng.uniform(-0.2 * h, 0.2 * h, pts.shape)
uop = UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-6, vol=h * h)
sh = ShardedUnstructuredOp(uop)  # global 1D mesh over all 4 devices
assert sh.layout == "offsets", f"expected offsets, got {sh.layout}"
uu = rng.normal(size=uop.n)
ug = multihost.put_global(uu, NamedSharding(sh.mesh, PartitionSpec()))
# eager apply: shard_map passes the op's global weight arrays as runtime
# ARGUMENTS; wrapping apply in an outer jit would capture them as closure
# constants, which multi-controller JAX rejects (the grid solvers learned
# the same lesson in round 3 — sources as jit arguments, docs/round3.md)
out = multihost.fetch_global(sh.apply(ug))
multihost.assert_same_on_all_hosts(out, "unstructured offsets")
erru = float(np.abs(out - uop.apply_np(uu)).max())
assert erru < 1e-12, f"unstructured offsets deviates by {erru:.3e}"
print(f"MH-OK p{pid} unstructured err={erru:.2e}", flush=True)

# ...and the full SOLVER loop on the sharded op, multi-controller: state
# placed via put_global, the op's weight arrays threaded through the jit'd
# scan as arguments, result fetched with a process all-gather — the
# manufactured-solution contract must hold in every process
from nonlocalheatequation_tpu.ops.unstructured import (  # noqa: E402
    UnstructuredSolver,
)

# checkpointing on: the chunked runner + final fetch must both route
# through the process all-gather (a plain np.asarray would raise on a
# cross-process array); the shared path is keyed by the coordinator port
ck_path = f"/tmp/mh-unstruct-ck-{coord.rsplit(':', 1)[1]}.npz"
sol = UnstructuredSolver(sh, nt=3, backend="jit",
                         checkpoint_path=ck_path, ncheckpoint=2)
sol.test_init()
us_final = sol.do_work()
multihost.assert_same_on_all_hosts(us_final, "unstructured solver")
assert sol.error_l2 / uop.n <= 1e-6, f"contract: {sol.error_l2 / uop.n:.3e}"
o_sol = UnstructuredSolver(uop, nt=3, backend="oracle")
o_sol.test_init()
err_sol = float(np.abs(us_final - o_sol.do_work()).max())
assert err_sol < 1e-12, f"solver deviates from oracle by {err_sol:.3e}"
print(f"MH-OK p{pid} unstructured-solver err={err_sol:.2e}", flush=True)
