"""One controller of the multi-process loopback solve.

Run by tests/test_multihost.py (not collected by pytest — no test_ prefix):
``python multihost_child.py <coordinator> <num_processes> <process_id>``.
The parent sets each process's local device count via XLA_FLAGS (equal by
default, UNEVEN in the split test) and passes the expected global device
total in ``MH_NDEV``; the meshes SPAN the process boundary, so the
shard_map halo exchange rides the cross-process (gloo) transport — the DCN
analog of the reference's multi-locality parcelport
(src/2d_nonlocal_distributed.cpp's get_data RPCs under srun -n N,
/root/reference/README.md:64-72).

``MH_LEGS`` selects legs (comma list, default all):

* ``2d``       — 16 x (8*my) grid on a (2, my) mesh at eps=3 (one-hop halo)
  and eps=9 (multi-hop ring), my = ndev//2; cross-host determinism and
  <=1e-12 agreement with the serial oracle.
* ``superstep``— the communication-avoiding K*eps exchange across the
  process boundary.
* ``3d``       — 8^3 on a (2,2,ndev//4 or 1) mesh at eps=2/eps=5.
* ``unstructured`` — sharded-offsets (DIA) op + full solver loop,
  multi-controller, incl. checkpoint write.
* ``crash2d``  — run a LONG checkpointed 2D distributed solve (nt=400,
  ncheckpoint=2 to ``MH_CK``); the parent SIGKILLs this job mid-flight
  (one process first, then the rest) — the checkpoint on disk must stay
  loadable (atomic tmp+rename under a hard kill).
* ``resume2d`` — resume ``MH_CK`` on THIS topology (any process count /
  mesh shape) and run to ``MH_NT_TOTAL``; must match the serial oracle's
  full trajectory to 1e-12 — kill-one + resume across a DIFFERENT process
  count (VERDICT r4 #6).
* ``crashu`` / ``resumeu`` — the same hard-kill + cross-topology resume
  pair for the SHARDED-OFFSETS unstructured path (VERDICT r4 #6 names
  both the grid SPMD and sharded-offsets paths): every process rebuilds
  the identical jittered cloud (seed contract), the checkpointed state
  is the global node vector, and the resume topology's process count
  need not match the writer's.

Each leg prints one ``MH-OK p<pid> ...`` line the parent test greps for.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

coord, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
LEGS = set(os.environ.get("MH_LEGS", "2d,superstep,3d,unstructured")
           .split(","))

from nonlocalheatequation_tpu.parallel import multihost  # noqa: E402

assert multihost.init_from_env(coord, nproc, pid), "explicit init must run"
assert jax.process_count() == nproc
ndev = int(os.environ.get("MH_NDEV", 2 * nproc))
assert len(jax.devices()) == ndev, (
    f"expected {ndev} global devices, got {len(jax.devices())}")

from nonlocalheatequation_tpu.models.solver2d import Solver2D  # noqa: E402
from nonlocalheatequation_tpu.parallel.distributed2d import (  # noqa: E402
    Solver2DDistributed,
)
from nonlocalheatequation_tpu.parallel.mesh import make_mesh  # noqa: E402

# mesh (2, my) over ALL global devices; the grid keeps 8x8 tiles so the
# eps=3 leg stays one-hop and eps=9 stays multi-hop at any my
MY = ndev // 2
NX, NY = 16, 8 * MY

def _sharded_cloud_op():
    """The canonical cloud op (tests.test_unstructured_sharded.cloud_op —
    identical in every process by seed contract), wrapped as the
    sharded-offsets operator over the process-spanning 1D mesh."""
    from tests.test_unstructured_sharded import cloud_op

    from nonlocalheatequation_tpu.ops.unstructured import (
        ShardedUnstructuredOp,
    )

    uop = cloud_op()
    return uop, ShardedUnstructuredOp(uop)


if "2d" in LEGS:
    # eps=3 = one-hop band exchange, eps=9 = multi-hop ring (the
    # long-horizon path), both crossing the process boundary
    for eps in (3, 9):
        mesh = make_mesh(2, MY)
        d = Solver2DDistributed(NX, NY, 1, 1, nt=3, eps=eps, k=1.0, dt=1e-4,
                                dh=1.0 / NX, mesh=mesh)
        d.test_init()
        ud = d.do_work()
        multihost.assert_same_on_all_hosts(ud, f"solution eps={eps}")
        o = Solver2D(NX, NY, 3, eps=eps, k=1.0, dt=1e-4, dh=1.0 / NX,
                     backend="oracle")
        o.test_init()
        uo = o.do_work()
        err = float(np.abs(ud - uo).max())
        assert err < 1e-12, f"eps={eps}: deviates from serial oracle by {err:.3e}"
        print(f"MH-OK p{pid} eps={eps} err={err:.2e}", flush=True)

if "superstep" in LEGS:
    # communication-avoiding superstep across the PROCESS boundary: one
    # K*eps-wide exchange per K steps over the gloo transport (the DCN
    # analog — the latency-bound regime the schedule exists for)
    o = Solver2D(NX, NY, 3, eps=3, k=1.0, dt=1e-4, dh=1.0 / NX,
                 backend="oracle")
    o.test_init()
    uo = o.do_work()
    ds = Solver2DDistributed(NX, NY, 1, 1, nt=3, eps=3, k=1.0,
                             dt=1e-4, dh=1.0 / NX, mesh=make_mesh(2, MY),
                             superstep=2)
    ds.test_init()
    us = ds.do_work()
    multihost.assert_same_on_all_hosts(us, "superstep solution")
    errs = float(np.abs(us - uo).max())
    assert errs < 1e-12, f"superstep deviates by {errs:.3e}"
    print(f"MH-OK p{pid} superstep err={errs:.2e}", flush=True)

if "3d" in LEGS:
    # 3D over a (2, 2, mz) mesh — same cross-process halo, one more axis:
    # eps=2 is the one-hop band exchange, eps=5 > shard edge the multi-hop
    # ring, mirroring the 2D pair above
    from nonlocalheatequation_tpu.models.solver3d import Solver3D  # noqa: E402
    from nonlocalheatequation_tpu.parallel.distributed3d import (  # noqa: E402
        Solver3DDistributed,
    )
    from nonlocalheatequation_tpu.parallel.mesh import make_mesh_3d  # noqa: E402

    MZ = ndev // 4 if ndev % 4 == 0 and ndev >= 4 else 1
    for eps3 in (2, 5):
        mesh3 = make_mesh_3d(2, 2, MZ)
        d3 = Solver3DDistributed(8, 8, 8, nt=2, eps=eps3, k=1.0, dt=1e-4,
                                 dh=0.05, mesh=mesh3)
        d3.test_init()
        u3 = d3.do_work()
        multihost.assert_same_on_all_hosts(u3, f"3d solution eps={eps3}")
        o3 = Solver3D(8, 8, 8, 2, eps=eps3, k=1.0, dt=1e-4, dh=0.05,
                      backend="oracle")
        o3.test_init()
        err3 = float(np.abs(u3 - o3.do_work()).max())
        assert err3 < 1e-12, (
            f"3d eps={eps3}: deviates from serial oracle by {err3:.3e}")
        print(f"MH-OK p{pid} 3d eps={eps3} err={err3:.2e}", flush=True)

if "unstructured" in LEGS:
    # unstructured offsets (DIA) over the process-spanning 1D mesh: per-
    # shard diagonal weights + ppermute halo bands crossing the gloo
    # transport — the gather-free multichip unstructured path, multi-
    # controller.  Every process builds the identical op (same seed: the
    # init contract).
    from jax.sharding import NamedSharding, PartitionSpec  # noqa: E402

    from nonlocalheatequation_tpu.ops.unstructured import (  # noqa: E402
        UnstructuredSolver,
    )

    uop, sh = _sharded_cloud_op()  # global 1D mesh over all devices
    rng = np.random.default_rng(1)  # post-build draws, same in every process
    assert sh.layout == "offsets", f"expected offsets, got {sh.layout}"
    uu = rng.normal(size=uop.n)
    ug = multihost.put_global(uu, NamedSharding(sh.mesh, PartitionSpec()))
    # eager apply: shard_map passes the op's global weight arrays as runtime
    # ARGUMENTS; wrapping apply in an outer jit would capture them as
    # closure constants, which multi-controller JAX rejects (the grid
    # solvers learned the same lesson in round 3 — sources as jit
    # arguments, docs/round3.md)
    out = multihost.fetch_global(sh.apply(ug))
    multihost.assert_same_on_all_hosts(out, "unstructured offsets")
    erru = float(np.abs(out - uop.apply_np(uu)).max())
    assert erru < 1e-12, f"unstructured offsets deviates by {erru:.3e}"
    print(f"MH-OK p{pid} unstructured err={erru:.2e}", flush=True)

    # ...and the full SOLVER loop on the sharded op, multi-controller:
    # state placed via put_global, the op's weight arrays threaded through
    # the jit'd scan as arguments, result fetched with a process
    # all-gather — the manufactured-solution contract must hold in every
    # process.  Checkpointing on: the chunked runner + final fetch must
    # both route through the process all-gather (a plain np.asarray would
    # raise on a cross-process array); the shared path is keyed by the
    # coordinator port
    ck_path = f"/tmp/mh-unstruct-ck-{coord.rsplit(':', 1)[1]}.npz"
    sol = UnstructuredSolver(sh, nt=3, backend="jit",
                             checkpoint_path=ck_path, ncheckpoint=2)
    sol.test_init()
    us_final = sol.do_work()
    multihost.assert_same_on_all_hosts(us_final, "unstructured solver")
    assert sol.error_l2 / uop.n <= 1e-6, f"contract: {sol.error_l2 / uop.n:.3e}"
    o_sol = UnstructuredSolver(uop, nt=3, backend="oracle")
    o_sol.test_init()
    err_sol = float(np.abs(us_final - o_sol.do_work()).max())
    assert err_sol < 1e-12, f"solver deviates from oracle by {err_sol:.3e}"
    print(f"MH-OK p{pid} unstructured-solver err={err_sol:.2e}", flush=True)

    # ...and the communication-avoiding superstep on the same sharded op,
    # cross-process: one K*pad-wide ring exchange per K steps over the
    # gloo transport (fits when K*pad <= block, i.e. few enough shards)
    if sh.superstep_fits(2):
        ss = UnstructuredSolver(sh, nt=3, backend="jit", superstep=2)
        ss.test_init()
        uss = ss.do_work()
        multihost.assert_same_on_all_hosts(uss, "unstructured superstep")
        err_ss = float(np.abs(uss - o_sol.u).max())
        assert err_ss < 1e-12, f"superstep deviates by {err_ss:.3e}"
        print(f"MH-OK p{pid} unstructured-superstep err={err_ss:.2e}",
              flush=True)

if "crashu" in LEGS:
    # sharded-offsets analog of crash2d: a long checkpointed run the
    # parent SIGKILLs mid-flight; the checkpoint must stay loadable
    from nonlocalheatequation_tpu.ops.unstructured import (  # noqa: E402
        UnstructuredSolver,
    )

    _, shc = _sharded_cloud_op()
    solc = UnstructuredSolver(shc, nt=400, backend="jit",
                              checkpoint_path=os.environ["MH_CK"],
                              ncheckpoint=2)
    solc.test_init()
    print(f"MH-CRASH-RUNNING p{pid}", flush=True)
    solc.do_work()
    print(f"MH-UNEXPECTED p{pid} crashu leg finished", flush=True)

if "resumeu" in LEGS:
    # resume the killed unstructured job's checkpoint on THIS topology
    # and run to MH_NT_TOTAL; must match the f64 oracle trajectory
    from nonlocalheatequation_tpu.ops.unstructured import (  # noqa: E402
        UnstructuredSolver,
    )

    uopr, shr = _sharded_cloud_op()
    nt_total = int(os.environ["MH_NT_TOTAL"])
    solr = UnstructuredSolver(shr, nt=nt_total, backend="jit")
    solr.test_init()
    solr.resume(os.environ["MH_CK"])
    assert solr.t0 > 0, "resume must continue mid-trajectory, not restart"
    ur = solr.do_work()
    multihost.assert_same_on_all_hosts(ur, "resumed unstructured")
    osol = UnstructuredSolver(uopr, nt=nt_total, backend="oracle")
    osol.test_init()
    erru = float(np.abs(ur - osol.do_work()).max())
    assert erru < 1e-12, f"resumed run deviates from oracle by {erru:.3e}"
    print(f"MH-OK p{pid} resumeu t0={solr.t0} err={erru:.2e}", flush=True)

if "crash2d" in LEGS:
    # long checkpointed run the parent will SIGKILL mid-flight; nothing
    # after do_work() is expected to execute
    d = Solver2DDistributed(16, 16, 1, 1, nt=400, eps=3, k=1.0, dt=1e-4,
                            dh=1.0 / 16, mesh=make_mesh(2, MY),
                            checkpoint_path=os.environ["MH_CK"],
                            ncheckpoint=2)
    d.test_init()
    print(f"MH-CRASH-RUNNING p{pid}", flush=True)
    d.do_work()
    print(f"MH-UNEXPECTED p{pid} crash leg finished", flush=True)

if "resume2d" in LEGS:
    # resume the killed job's checkpoint on THIS topology (the process
    # count and mesh shape need not match the writer's: the checkpoint is
    # the GLOBAL state, CheckpointMixin validates the physics params) and
    # run to MH_NT_TOTAL; the full trajectory must match the serial oracle
    nt_total = int(os.environ["MH_NT_TOTAL"])
    d = Solver2DDistributed(16, 16, 1, 1, nt=nt_total, eps=3, k=1.0,
                            dt=1e-4, dh=1.0 / 16, mesh=make_mesh(2, MY))
    d.test_init()
    d.resume(os.environ["MH_CK"])
    assert d.t0 > 0, "resume must continue mid-trajectory, not restart"
    ur = d.do_work()
    multihost.assert_same_on_all_hosts(ur, "resumed solution")
    o = Solver2D(16, 16, nt_total, eps=3, k=1.0, dt=1e-4, dh=1.0 / 16,
                 backend="oracle")
    o.test_init()
    err = float(np.abs(ur - o.do_work()).max())
    assert err < 1e-12, f"resumed run deviates from oracle by {err:.3e}"
    print(f"MH-OK p{pid} resume2d t0={d.t0} err={err:.2e}", flush=True)
