"""Test env: force CPU with 8 virtual devices (multi-chip stand-in) and f64.

Mirrors the reference's test strategy (SURVEY.md section 4): the distributed
ctest runs on a single host; we use XLA's host-platform device-count knob so
sharding/collective paths execute with real (virtual) devices, the same way
the driver's dryrun validates multi-chip compilation.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # the ambient env selects the TPU ('axon')
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# kernel experiment knobs leaked from a developer shell must not silently
# switch the paths the suite compares (e.g. the resident-vs-scan oracles)
for _knob in ("NLHEAT_RESIDENT", "NLHEAT_SUPERSTEP", "NLHEAT_AUTOTUNE",
              "NLHEAT_LANE_RUNS", "NLHEAT_TM", "NLHEAT_DONATE",
              "NLHEAT_TUNE_PRECISION", "NLHEAT_TUNE_BATCH",
              "NLHEAT_FAULT_PLAN", "BENCH_PRECISION", "BENCH_ENSEMBLE",
              "BENCH_SERVE", "BENCH_SERVE_FAULTS",
              # a leaked event-log/trace path must not make the suite
              # write telemetry files (obs/export.py, cli obs_session)
              "NLHEAT_EVENT_LOG", "NLHEAT_TRACE", "BENCH_TRACE",
              "NLHEAT_FLIGHT_DIR", "BENCH_TRACE_FLEET",
              # a leaked AOT store dir must not let suite programs load
              # stale executables (or write new ones) across test runs
              "NLHEAT_PROGRAM_STORE", "NLHEAT_PROGRAM_CACHE_CAP",
              # a leaked picker ladder / expo opt-in / fleet-TTA knob
              # must not silently reroute the engine-picker tests
              # (serve/picker.py) or arm the ttafleet bench rung
              "NLHEAT_PICK_STAGES", "NLHEAT_PICK_EXPO",
              "BENCH_TTA_FLEET",
              # leaked session-tier knobs (serve/sessions.py) must not
              # silently change the suite's budgets, checkpoint cadence,
              # or preview stride — the same hygiene as every prior
              # serve-tier knob family
              "NLHEAT_SESSION_BUDGET", "NLHEAT_SESSION_CKPT_EVERY",
              "NLHEAT_SESSION_PREVIEW", "BENCH_SESSION",
              # a leaked sharded-fft kill-switch / fft-gang bench knob
              # must not silently disable the spectral tier under test
              # (ops/spectral_sharded.py) or arm the fftgang bench rung
              "NLHEAT_FFT_SHARDED", "BENCH_FFT_GANG",
              # the mesh registry knobs (ISSUE 17, serve/meshes.py): an
              # ambient mesh dir would make mesh-keyed cases resolve
              # against a user registry instead of each test's tmp one,
              # and BENCH_MESH must not arm its bench rung mid-suite
              "NLHEAT_MESH_DIR", "NLHEAT_MESH_MAX_NODES", "BENCH_MESH",
              # the SLO ledger knobs (ISSUE 20, obs/slo.py): an ambient
              # NLHEAT_SLO would arm auditing (and the live rate
              # write-back) inside every serve test, a leaked band/
              # window would reshape the drift tests' thresholds, and
              # BENCH_SLO must not arm its bench rung mid-suite
              "NLHEAT_SLO", "NLHEAT_SLO_BAND", "NLHEAT_SLO_WINDOW",
              "NLHEAT_SLO_MIN", "NLHEAT_SLO_LIVE", "BENCH_SLO"):
    os.environ.pop(_knob, None)
# "" DISABLES autotune-cache persistence (unset means the per-user default
# file since tuning became the on-TPU default): the suite must neither read
# stale winners from nor write CPU-interpreted probes into ~/.cache/nlheat
os.environ["NLHEAT_AUTOTUNE_CACHE"] = ""

import jax

# The axon TPU plugin ignores the env var; the config knob does force CPU.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
