"""Pod-scale fleet (ISSUE 12): TCP worker transport + sharded gang tier.

Covers the tentpole contracts end to end on the f64 8-virtual-device
CPU suite:

* loopback-TCP bit-identity: a fleet whose workers dial in over sockets
  (``--worker-connect`` + hello) serves the same case set bit-identical
  to the offline engine (and therefore to the in-process pipe router,
  whose identity test_router.py pins against the same oracle),
* warm-add of a TCP worker: the newcomer inherits buckets and serves
  them from the shared AOT program store — ``store_hits >= 1``,
  ``programs_built == 0`` (the zero-retrace spy, now over sockets),
* the sharded case class: 2D grids above ``shard_threshold`` dispatch
  to the gang replica (an N-device mesh running whole distributed
  solves, ``comm='fused'`` where require_fused accepts) and return
  bit-identical to the offline ``solve_case_sharded`` /
  ``Solver2DDistributed`` path,
* ``die@`` chaos on a socket worker MID-SHARDED-CASE: reader-EOF death
  detection, gang respawn, lossless duplicate-free re-route — the PR 10
  guarantees unchanged over TCP,
* frame-protocol hardening: malformed/oversized/truncated length
  prefixes and mid-frame disconnects read as ``None`` (replica death),
  never a crash or a hung reader — the fuzz-style refusals next to
  test_router.py's parse refusals,
* the socket trust boundary: non-loopback binds refuse without a
  token, a wrong-token hello is dropped before anything is unpickled,
  and a garbage connection cannot crash a serving router.

Worker processes are real (subprocess + jax import each), so the fleet
tests batch several assertions per spawned router to hold the tier-1
budget.
"""

import io
import socket
import struct
import threading

import numpy as np
import pytest

import jax

from nonlocalheatequation_tpu.parallel.gang import solve_case_sharded
from nonlocalheatequation_tpu.parallel.mesh_axes import pick_gang_devices
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)
from nonlocalheatequation_tpu.serve.router import ReplicaRouter
from nonlocalheatequation_tpu.serve.transport import (
    LEN,
    MAX_FRAME_BYTES,
    PipeTransport,
    SocketTransport,
    make_transport,
    read_frame,
    write_frame,
    write_json_frame,
)

assert jax.config.jax_enable_x64  # the oracle contract (conftest forces it)


def make_cases(n, grid=16, nt=4, buckets=2, seed=0):
    rng = np.random.default_rng(seed)
    return [EnsembleCase(shape=(grid, grid), nt=nt + (i % buckets), eps=2,
                         k=1.0, dt=1e-5, dh=1.0 / grid, test=False,
                         u0=rng.normal(size=(grid, grid)))
            for i in range(n)]


def make_sharded(n, grid=24, nt=3, seed=1):
    """Cases above a grid=16 threshold (24^2 = 576 > 256), divisible by
    the virtual-device mesh shapes choose_mesh_for_grid picks."""
    rng = np.random.default_rng(seed)
    return [EnsembleCase(shape=(grid, grid), nt=nt + i, eps=2, k=1.0,
                         dt=1e-5, dh=1.0 / grid, test=False,
                         u0=rng.normal(size=(grid, grid)))
            for i in range(n)]


def offline(cases):
    return EnsembleEngine(method="sat", batch_sizes=(1,)).run(cases)


# ---------------------------------------------------------------------------
# the TCP fleet (real worker processes dialing in over loopback)
# ---------------------------------------------------------------------------


def test_tcp_fleet_bit_identity_warm_add_and_garbage_conn(tmp_path):
    store = str(tmp_path / "store")
    cases = make_cases(6, buckets=2)
    want = offline(cases)
    with ReplicaRouter(replicas=1, method="sat", batch_sizes=(1,),
                       transport="tcp", program_store=store,
                       max_replicas=2) as router:
        assert router.metrics()["transport"] == "tcp"
        got = router.serve_cases(cases)
        # bit-identical to the offline engine over sockets (the pipe
        # router is pinned against the same oracle in test_router.py,
        # so this also pins tcp == pipe)
        assert all(np.array_equal(a, b) for a, b in zip(want, got, strict=True))
        # a garbage connection to the transport listener (port scanner,
        # confused client) must not perturb the serving fleet
        port = router._transport.port
        with socket.create_connection(("127.0.0.1", port)) as s:
            s.sendall(b"\xff" * 64)
        got2 = router.serve_cases(cases)
        assert all(np.array_equal(a, b) for a, b in zip(want, got2, strict=True))
        assert router.metrics()["deaths"] == 0
        # warm-add over TCP: the newcomer dials in, inherits a fair
        # share of the buckets (1 of 2), and serves it from the shared
        # store — store_hits >= 1, ZERO programs built (the
        # zero-retrace spy, now over sockets)
        rid = router.add_replica()
        assert len(router._replicas[rid].buckets) == 1
        moved = next(iter(router._replicas[rid].buckets))
        assert router._owner[moved] == rid
        got3 = router.serve_cases(cases)
        assert all(np.array_equal(a, b) for a, b in zip(want, got3, strict=True))
        stats = router.refresh_stats()
        new = stats[rid]["metrics"]
        assert new["cases"] >= 1
        assert new["store"]["hits"] >= 1
        assert new["programs_built"] == 0


def test_gang_sharded_bit_identity_and_socket_chaos():
    small = make_cases(2, buckets=1)
    big = make_sharded(2)
    want_small = offline(small)
    # the offline oracle: the SAME adapter the gang worker calls, in
    # THIS process on the same 8 virtual devices — method='sat' is not
    # pallas, so require_fused refuses and the solve honestly falls
    # back to the collective transport (recorded in info)
    want_big = []
    ocache: dict = {}
    for c in big:
        v, info = solve_case_sharded(c, ndevices=8, comm="fused",
                                     method="sat", solver_cache=ocache)
        assert info["comm"] == "collective"  # sat -> fused refused
        assert info["devices"] == 8
        want_big.append(v)
    # die@2: the THIRD case-forward is the first sharded case — the
    # gang replica is SIGKILLed with it in flight, mid-distributed-
    # solve, over a socket; the reader's EOF must re-route losslessly
    # after the gang respawn
    with ReplicaRouter(replicas=1, method="sat", batch_sizes=(1,),
                       transport="tcp", shard_threshold=16 * 16,
                       gang_devices=8, faults="die@2",
                       respawn=True) as router:
        handles = [router.submit(c) for c in small + big]
        router.drain(timeout_s=600)
        m = router.metrics()
        assert m["deaths"] == 1
        assert m["requeued"] >= 1
        assert m["sharded_cases"] == 2
        assert len(m["gang"]) == 1  # the respawned gang replica
        # no lost results, no duplicates, every result bit-identical —
        # small to the engine oracle, sharded to the offline
        # distributed solve
        for h, w in zip(handles, want_small + want_big, strict=True):
            assert h.error is None
            assert np.array_equal(h.result, w)
        # the gang replica answers the stats pull flagged gang=True and
        # stays OUT of the small-fleet scale telemetry
        stats = router.refresh_stats()
        gid = m["gang"][0]
        assert stats[gid].get("gang") is True
        assert stats[gid]["metrics"]["cases"] >= 1
        assert router._telemetry.rate(gid) == 0.0  # never recorded
        # the gang replica cannot be drained out from under the tier
        with pytest.raises(ValueError, match="gang replica"):
            router.drain_replica(gid)


def test_gang_fused_engages_on_pallas():
    # the comm='fused' half of the acceptance: a pallas-method sharded
    # solve runs the fused halo family (require_fused accepts) and
    # still matches the collective oracle bitwise — the PR 6 contract
    # through the case adapter
    case = EnsembleCase(shape=(16, 16), nt=3, eps=2, k=1.0, dt=1e-4,
                        dh=0.02, test=True, u0=None)
    vf, inf = solve_case_sharded(case, ndevices=8, comm="fused",
                                 method="pallas")
    assert inf["comm"] == "fused"
    vc, inc = solve_case_sharded(case, ndevices=8, comm="collective",
                                 method="pallas")
    assert inc["comm"] == "collective"
    assert np.array_equal(vf, vc)
    # manufactured contract holds through the adapter
    assert inf["error_l2"] / (16 * 16) <= 1e-6
    # and the spatial axes ride ICI per the hybrid rules
    assert inf["axes"] == {"x": "ici", "y": "ici"}


# ---------------------------------------------------------------------------
# frame-protocol hardening (fuzz-style refusals, no processes)
# ---------------------------------------------------------------------------


def test_frame_refusals_truncated_oversized_midframe():
    # a healthy round trip first
    buf = io.BytesIO()
    write_frame(buf, {"op": "case", "id": 7})
    buf.seek(0)
    assert read_frame(buf) == {"op": "case", "id": 7}
    # truncated length prefix -> None (death), not a struct error
    assert read_frame(io.BytesIO(b"\x01\x02\x03")) is None
    # OVERSIZED length prefix (garbage read as u64) -> None, and never
    # a giant allocation
    evil = LEN.pack(MAX_FRAME_BYTES + 1) + b"x"
    assert read_frame(io.BytesIO(evil)) is None
    # ASCII garbage where the prefix should be: reads as ~10^18 -> None
    assert read_frame(io.BytesIO(b"GET / HTTP/1.1\r\n\r\n")) is None
    # mid-frame disconnect (header promises more than arrives) -> None
    short = LEN.pack(100) + b"only-ten-b"
    assert read_frame(io.BytesIO(short)) is None
    # empty stream == clean EOF -> None
    assert read_frame(io.BytesIO(b"")) is None


def test_socket_transport_token_and_hello_refusals():
    # non-loopback bind without a token refuses at construction: the
    # frames are pickle and the trust boundary is explicit
    with pytest.raises(ValueError, match="token"):
        SocketTransport(host="0.0.0.0")
    st = SocketTransport(token="s3cret")
    try:
        results = {}

        def accept():
            try:
                results["conn"] = st._accept(5, timeout_s=10)
            except Exception as e:  # noqa: BLE001
                results["err"] = e

        t = threading.Thread(target=accept, daemon=True)
        t.start()
        # 1) wrong token: the hello parses but fails the HMAC compare —
        # the connection is closed WITHOUT anything being unpickled
        bad = socket.create_connection(("127.0.0.1", st.port))
        f = bad.makefile("wb")
        write_json_frame(f, {"op": "hello", "replica": 5,
                             "token": "wrong"})
        assert bad.recv(1) == b""  # server closed on us
        bad.close()
        # 2) garbage instead of a hello: dropped the same way
        junk = socket.create_connection(("127.0.0.1", st.port))
        junk.sendall(struct.pack("<Q", 1 << 40))  # oversized hello
        assert junk.recv(1) == b""
        junk.close()
        # 3) the correct hello is accepted
        good = socket.create_connection(("127.0.0.1", st.port))
        gf = good.makefile("wb")
        write_json_frame(gf, {"op": "hello", "replica": 5,
                              "token": "s3cret"})
        t.join(timeout=15)
        assert "conn" in results, results.get("err")
        # and the accepted channel speaks real pickle frames both ways
        conn = results["conn"]
        write_frame(conn.makefile("wb"), {"op": "ready", "replica": 5})
        assert read_frame(good.makefile("rb")) == {"op": "ready",
                                                   "replica": 5}
        good.close()
        conn.close()
    finally:
        st.close()


def test_transport_resolution_refusals():
    assert isinstance(make_transport(None), PipeTransport)
    assert isinstance(make_transport("pipe"), PipeTransport)
    with pytest.raises(ValueError, match="worker_token"):
        make_transport("pipe", token="s")
    with pytest.raises(ValueError, match="unknown transport"):
        make_transport("carrier-pigeon")
    st = SocketTransport(token="t")
    try:
        assert make_transport(st, token="t") is st
        with pytest.raises(ValueError, match="one credential"):
            make_transport(st, token="other")
    finally:
        st.close()
    tcp = make_transport("tcp")
    try:
        assert tcp.name == "tcp" and tcp.host == "127.0.0.1"
    finally:
        tcp.close()


# ---------------------------------------------------------------------------
# the gang adapter + device picking (pure units)
# ---------------------------------------------------------------------------


def test_pick_gang_devices_whole_granules_first():
    devs = jax.devices()  # 8 virtual CPU devices, one granule
    assert pick_gang_devices(8) == devs
    assert pick_gang_devices(3) == devs[:3]
    with pytest.raises(ValueError, match="1 <= n"):
        pick_gang_devices(0)
    with pytest.raises(ValueError, match="1 <= n"):
        pick_gang_devices(99)

    class FakeDev:
        def __init__(self, i, granule):
            self.id = i
            self.process_index = granule

        def __repr__(self):
            return f"d{self.id}@g{self.process_index}"

    # two granules of 4: n=4 stays inside ONE granule (no DCN-striding
    # spatial axis), n=6 fills the first granule then takes 2 more
    fleet = [FakeDev(i, i // 4) for i in range(8)]
    picked = pick_gang_devices(4, fleet)
    assert {d.process_index for d in picked} == {0}
    picked6 = pick_gang_devices(6, fleet)
    assert [d.process_index for d in picked6] == [0, 0, 0, 0, 1, 1]


def test_gang_solver_cache_is_bounded_lru():
    # every entry pins full-grid state + compiled programs: the memo
    # must evict (PR 9's PROGRAM_CACHE_CAP lesson) — and eviction must
    # never change results
    cache: dict = {}
    cases = [EnsembleCase(shape=(24, 24), nt=2 + i, eps=2, k=1.0,
                          dt=1e-5, dh=1 / 24, test=True, u0=None)
             for i in range(3)]
    outs = [solve_case_sharded(c, ndevices=2, method="sat",
                               solver_cache=cache, cache_cap=2)[0]
            for c in cases]
    assert len(cache) == 2  # the oldest signature evicted
    # a re-solve of the evicted signature reconstructs, bit-identical
    again = solve_case_sharded(cases[0], ndevices=2, method="sat",
                               solver_cache=cache, cache_cap=2)[0]
    assert np.array_equal(again, outs[0])
    with pytest.raises(ValueError, match="cache_cap"):
        solve_case_sharded(cases[0], ndevices=2, method="sat",
                           solver_cache={}, cache_cap=-1)


def test_solve_case_sharded_refusals():
    ok = make_sharded(1)[0]
    with pytest.raises(ValueError, match="2D"):
        solve_case_sharded(EnsembleCase(shape=(8,), nt=2, eps=1, k=1.0,
                                        dt=1e-5, dh=0.1, test=True),
                           ndevices=2)
    with pytest.raises(ValueError, match="comm"):
        solve_case_sharded(ok, comm="bogus")
    prod = make_sharded(1)[0]
    prod.u0 = None
    with pytest.raises(ValueError, match="needs an"):
        solve_case_sharded(prod, ndevices=2, method="sat")


def test_router_sharded_ctor_refusals():
    with pytest.raises(ValueError, match="shard_threshold"):
        ReplicaRouter(replicas=1, shard_threshold=-1)
    with pytest.raises(ValueError, match="gang_comm"):
        ReplicaRouter(replicas=1, shard_threshold=64, gang_comm="bogus")
    with pytest.raises(ValueError, match="gang_devices"):
        ReplicaRouter(replicas=1, shard_threshold=64, gang_devices=0)
    with pytest.raises(ValueError, match="unknown transport"):
        ReplicaRouter(replicas=1, transport="bogus")
    with pytest.raises(ValueError, match="worker_token"):
        ReplicaRouter(replicas=1, worker_token="s")  # pipe + token


def test_fleet_tcp_ab_refuses_bucket_starvation():
    from nonlocalheatequation_tpu.serve.router import fleet_tcp_ab

    with pytest.raises(ValueError, match="distinct buckets"):
        fleet_tcp_ab({}, make_cases(4, buckets=1), 2, None)
