"""Distributed super-stepping + engine picker (ISSUE 13).

Pins the PR's tentpole contracts on the f64 8-virtual-device CPU suite:

* distributed rkc == the single-device rkc oracle <= 1e-12 across
  non-square meshes, eps 1/2/9 (multi-hop included), fused AND
  collective transports — per-stage exchange is elementwise-identical
  (bitwise here), stage batches recompute ring cells (1e-12 class),
* the manufactured contract holds at 9x the Euler-stable dt,
* the expo boundary correction (stages >= 1) measurably shrinks the
  collar defect; stages=0 stays the legacy interior-exact step,
* the engine picker: a deterministic unit table over (grid, accuracy,
  deadline) -> expected engine, loud refusal when no engine meets the
  deadline, the accuracy target never gambled, env-ladder/bf16/fft
  axes,
* picked engines served through the pipeline pool bit-identical to the
  offline sibling engine,
* gang sharded rkc bit-identical across the socket boundary (the fleet
  form of the same oracle), and the picked engine honored by BOTH the
  router's case classes through the HTTP front door,
* the distributed CLIs' stepper surface (the ISSUE 13 bugfix: they
  silently ignored the stepper axis): rc-2 over-bound refusal, expo and
  elastic refusals, a working distributed rkc batch row.
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.models.solver3d import Solver3D
from nonlocalheatequation_tpu.models.steppers import _make_expo_step
from nonlocalheatequation_tpu.ops.constants import (
    BF16_L2_BUDGET,
    c_2d,
    stable_dt,
)
from nonlocalheatequation_tpu.ops.nonlocal_op import NonlocalOp2D
from nonlocalheatequation_tpu.ops.stencil import horizon_mask_2d
from nonlocalheatequation_tpu.parallel.distributed2d import Solver2DDistributed
from nonlocalheatequation_tpu.parallel.distributed3d import Solver3DDistributed
from nonlocalheatequation_tpu.parallel.gang import solve_case_sharded
from nonlocalheatequation_tpu.parallel.mesh import make_mesh, make_mesh_3d
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)
from nonlocalheatequation_tpu.serve.picker import (
    EngineChoice,
    PickerRefusal,
    pick_engine,
)
from nonlocalheatequation_tpu.serve.server import ServePipeline

assert jax.config.jax_enable_x64  # the oracle contract (conftest forces it)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def euler_bound(eps: int, k: float, dh: float) -> float:
    wsum = float(np.asarray(horizon_mask_2d(eps), np.float64).sum())
    return stable_dt(c_2d(k, eps, dh), dh, 2, wsum)


def rkc_bound(eps: int, k: float, dh: float, stages: int) -> float:
    wsum = float(np.asarray(horizon_mask_2d(eps), np.float64).sum())
    return stable_dt(c_2d(k, eps, dh), dh, 2, wsum, "rkc", stages)


def serial_rkc(NX, NY, nt, eps, k, dt, dh, method, stages):
    s = Solver2D(NX, NY, nt, eps, k=k, dt=dt, dh=dh, backend="jit",
                 method=method, stepper="rkc", stages=stages)
    s.test_init()
    return s.do_work()


# ---------------------------------------------------------------------------
# distributed rkc vs the single-device rkc oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mesh_shape", [(4, 2), (2, 4), (8, 1)])
@pytest.mark.parametrize("eps", [1, 2, 9])
def test_distributed_rkc_matches_serial_oracle_collective(mesh_shape, eps):
    NX = NY = 48
    k, dh, nt, stages = 1.0, 0.05, 3, 4
    dt = 0.8 * rkc_bound(eps, k, dh, stages)
    want = serial_rkc(NX, NY, nt, eps, k, dt, dh, "conv", stages)
    mesh = make_mesh(*mesh_shape, jax.devices())
    d = Solver2DDistributed(
        NX // mesh_shape[0], NY // mesh_shape[1], *mesh_shape, nt, eps,
        k=k, dt=dt, dh=dh, mesh=mesh, method="conv", stepper="rkc",
        stages=stages)
    d.test_init()
    got = d.do_work()
    # per-stage exchange runs the SAME elementwise program over an
    # exchange that reconstructs the same neighborhoods: bitwise
    assert np.array_equal(got, want)
    assert np.abs(got - want).max() <= 1e-12  # the stated contract


@pytest.mark.parametrize("eps", [1, 2, 9])
def test_distributed_rkc_matches_serial_oracle_fused(eps):
    # comm='fused' (the pallas split kernel under the ppermute transport
    # off-TPU): the stage loop sits above make_fused_apply unchanged
    NX = NY = 48
    k, dh, nt, stages = 1.0, 0.05, 3, 4
    dt = 0.8 * rkc_bound(eps, k, dh, stages)
    want = serial_rkc(NX, NY, nt, eps, k, dt, dh, "pallas", stages)
    mesh = make_mesh(4, 2, jax.devices())
    d = Solver2DDistributed(12, 24, 4, 2, nt, eps, k=k, dt=dt, dh=dh,
                            mesh=mesh, method="pallas", comm="fused",
                            stepper="rkc", stages=stages)
    d.test_init()
    got = d.do_work()
    # the serial pallas kernel and its block decomposition differ by
    # last ulps at some eps (collective shows the same — an XLA fusion
    # artifact, not a transport one): the stated 1e-12 contract
    assert np.abs(got - want).max() <= 1e-12
    # fused IS bitwise against its own collective twin (the PR 6
    # contract, now under the stage loop)
    dc = Solver2DDistributed(12, 24, 4, 2, nt, eps, k=k, dt=dt, dh=dh,
                             mesh=mesh, method="pallas",
                             comm="collective", stepper="rkc",
                             stages=stages)
    dc.test_init()
    assert np.array_equal(got, dc.do_work())


@pytest.mark.parametrize("ksteps", [2, 3, 8])
def test_distributed_rkc_stage_batches(ksteps):
    # the communication-avoiding composition: ceil(s/K) exchange rounds
    # per step, ring cells recomputed locally — 1e-12 class vs the
    # per-stage form (and the serial oracle), test AND production modes
    NX = NY = 48
    eps, k, dh, nt, stages = 2, 1.0, 0.05, 3, 6
    dt = 0.8 * rkc_bound(eps, k, dh, stages)
    want = serial_rkc(NX, NY, nt, eps, k, dt, dh, "conv", stages)
    mesh = make_mesh(4, 2, jax.devices())
    d = Solver2DDistributed(12, 24, 4, 2, nt, eps, k=k, dt=dt, dh=dh,
                            mesh=mesh, method="conv", stepper="rkc",
                            stages=stages, superstep=ksteps)
    d.test_init()
    assert np.abs(d.do_work() - want).max() <= 1e-12
    # production (no manufactured source): same schedule, real u0
    rng = np.random.default_rng(0)
    u0 = rng.normal(size=(NX, NY))
    s = Solver2D(NX, NY, nt, eps, k=k, dt=dt, dh=dh, backend="jit",
                 method="conv", stepper="rkc", stages=stages)
    s.input_init(u0.ravel())
    d2 = Solver2DDistributed(12, 24, 4, 2, nt, eps, k=k, dt=dt, dh=dh,
                             mesh=mesh, method="conv", stepper="rkc",
                             stages=stages, superstep=ksteps)
    d2.input_init(u0.ravel())
    assert np.abs(d2.do_work() - s.do_work()).max() <= 1e-12


def test_distributed_rkc_manufactured_9x_euler_dt():
    # the speed claim's accuracy half: 9x the Euler-stable dt still
    # holds the manufactured 1e-6 contract on the distributed path
    NX = NY = 48
    eps, k, dh, stages = 2, 1.0, 0.01, 8
    dt = 9.0 * euler_bound(eps, k, dh)
    assert dt <= rkc_bound(eps, k, dh, stages)  # inside the rkc model
    mesh = make_mesh(4, 2, jax.devices())
    d = Solver2DDistributed(12, 24, 4, 2, 5, eps, k=k, dt=dt, dh=dh,
                            mesh=mesh, method="conv", stepper="rkc",
                            stages=stages)
    d.test_init()
    d.do_work()
    assert d.error_l2 / (NX * NY) <= 1e-6


def test_distributed_rkc_3d():
    from nonlocalheatequation_tpu.ops.constants import c_3d
    from nonlocalheatequation_tpu.ops.stencil import horizon_mask_3d

    n, eps, k, dh, nt, stages = 16, 2, 1.0, 0.0625, 3, 4
    wsum = float(np.asarray(horizon_mask_3d(eps), np.float64).sum())
    dt = 0.8 * stable_dt(c_3d(k, eps, dh), dh, 3, wsum, "rkc", stages)
    s = Solver3D(n, n, n, nt, eps, k=k, dt=dt, dh=dh, backend="jit",
                 method="sat", stepper="rkc", stages=stages)
    s.test_init()
    want = s.do_work()
    for K in (1, 2):
        d = Solver3DDistributed(
            n, n, n, nt, eps, k=k, dt=dt, dh=dh,
            mesh=make_mesh_3d(2, 2, 2, devices=jax.devices()),
            method="sat", stepper="rkc", stages=stages, superstep=K)
        d.test_init()
        assert np.abs(d.do_work() - want).max() <= 1e-12


def test_distributed_stepper_refusals():
    mesh = make_mesh(4, 2, jax.devices())
    kw = dict(nx=12, ny=24, npx=4, npy=2, nt=3, eps=2, k=1.0, dh=0.05,
              mesh=mesh, method="conv")
    # over-bound dt: refused at construction with the bound named
    bound = rkc_bound(2, 1.0, 0.05, 4)
    with pytest.raises(ValueError, match="RKC stability"):
        Solver2DDistributed(dt=bound * 1.01, stepper="rkc", stages=4,
                            **kw)
    # just inside: accepted
    Solver2DDistributed(dt=bound * 0.99, stepper="rkc", stages=4, **kw)
    # expo: whole-domain spectral embedding, refused on sharded blocks
    with pytest.raises(ValueError, match="whole-domain"):
        Solver2DDistributed(dt=1e-5, stepper="expo", **kw)
    with pytest.raises(ValueError, match="stages >= 2"):
        Solver2DDistributed(dt=1e-5, stepper="rkc", stages=1, **kw)


# ---------------------------------------------------------------------------
# the expo boundary correction
# ---------------------------------------------------------------------------


def test_expo_collar_correction_shrinks_defect():
    # boundary-loaded state, one big step vs a fine-substepped reference
    # (the collar defect vanishes as dt -> 0, so the 512-substep run is
    # the ground truth to ~1e-6 of the defect scale)
    n, eps, k, dh = 40, 3, 1.0, 0.05
    x = np.linspace(0, 1, n)
    u0 = np.outer(np.exp(-((x - 0.05) / 0.1) ** 2),
                  np.exp(-((x - 0.5) / 0.3) ** 2))
    T = 10.0 * euler_bound(eps, k, dh)

    def run(dt, nsteps, stages):
        op = NonlocalOp2D(eps, k, dt, dh, method="fft")
        step = _make_expo_step(op, None, None, jnp.float64, stages=stages)
        u = jnp.asarray(u0)
        for t in range(nsteps):
            u = step(u, t)
        return np.asarray(u)

    ref = run(T / 512, 512, 0)
    plain = np.abs(run(T, 1, 0) - ref).max()
    corr1 = np.abs(run(T, 1, 1) - ref).max()
    corr4 = np.abs(run(T, 1, 4) - ref).max()
    # measured on this probe: ~2.7x at S=1, ~18x at S=4 — gate with
    # slack so backend jitter cannot flake a real multiple
    assert corr1 <= 0.6 * plain
    assert corr4 <= 0.3 * corr1
    # the interior stays spectral-exact: far from the boundary the
    # corrected and plain steps agree to roundoff of the defect scale
    mid = slice(n // 2 - 4, n // 2 + 4)
    assert np.abs(run(T, 1, 1) - run(T, 1, 0))[mid, mid].max() \
        <= 1e-2 * plain


def test_expo_stages_zero_is_the_legacy_step():
    # stages=0 takes the untouched single-table branch: pin it against
    # the closed-form spectral update it implements
    from nonlocalheatequation_tpu.ops.spectral import fft_box
    from nonlocalheatequation_tpu.utils.compat import irfftn, rfftn

    n, eps, k, dh = 24, 2, 1.0, 0.05
    op = NonlocalOp2D(eps, k, 5e-3, dh, method="fft")
    rng = np.random.default_rng(1)
    u0 = rng.normal(size=(n, n))
    step = _make_expo_step(op, None, None, jnp.float64, stages=0)
    got = np.asarray(step(jnp.asarray(u0), 0))
    from nonlocalheatequation_tpu.models.steppers import _expo_tables

    E, _P = _expo_tables(op, (n, n), jnp.float64)
    box = fft_box((n, n), eps)
    pad = [(0, b - s) for s, b in zip((n, n), box, strict=True)]
    want = np.asarray(irfftn(E * rfftn(jnp.pad(jnp.asarray(u0), pad)),
                             s=box))[:n, :n]
    assert np.array_equal(got, want)


# ---------------------------------------------------------------------------
# the engine picker
# ---------------------------------------------------------------------------


def flat_rate(ms=1.0, fft_ms=None):
    """Deterministic rate_fn: every stencil apply costs ``ms``, fft
    ``fft_ms`` (defaults to 2x)."""
    fm = fft_ms if fft_ms is not None else 2.0 * ms

    def rate(method, shape, eps, precision):
        base = fm if method == "fft" else ms
        return base * (0.7 if precision == "bf16" else 1.0)

    return rate


def test_picker_unit_table():
    eps, k, dh = 2, 1.0, 0.01  # fine dh: tiny Euler bound
    eul = euler_bound(eps, k, dh)
    T = 30 * eul
    # loose accuracy, no deadline: rkc4 wins (fewest applies — 1 step
    # of 4 stages beats 38 Euler steps and the 2x-cost fft)
    ch = pick_engine((32, 32), eps, k, dh, T, 1e-6, rate_fn=flat_rate())
    assert (ch.stepper, ch.method, ch.precision) == ("rkc", "auto", "f32")
    assert ch.steps * ch.stages < T / (0.8 * eul)  # fewer applies
    assert ch.rates == "measured"
    # accuracy so tight the dt cap binds below the Euler bound: every
    # stepper needs the same step count, euler's 1 apply/step wins
    tight = pick_engine((32, 32), eps, k, dh, T, 1e-13,
                        rate_fn=flat_rate())
    assert tight.stepper == "euler"
    # the accuracy target is never gambled: the modeled error respects
    # the safety margin for every pick
    from nonlocalheatequation_tpu.serve.picker import ERR_SAFETY

    for c in (ch, tight):
        assert ERR_SAFETY * c.est_err <= 1e-6 + 1e-30 or c is tight
    # deadline: cheap-but-slow engines refuse, the pick fits the budget
    fits = pick_engine((32, 32), eps, k, dh, T, 1e-6,
                       deadline_ms=ch.est_ms * 1.01,
                       rate_fn=flat_rate())
    assert fits.est_ms <= ch.est_ms * 1.01
    with pytest.raises(PickerRefusal, match="deadline"):
        pick_engine((32, 32), eps, k, dh, T, 1e-6, deadline_ms=1e-9,
                    rate_fn=flat_rate())
    # sharded tier: fft (and expo) never compete
    nofft = pick_engine((32, 32), eps, k, dh, T, 1e-6, allow_fft=False,
                        rate_fn=flat_rate(fft_ms=1e-9))
    assert nofft.method != "fft"
    # cheap fft wins when allowed
    cheap_fft = pick_engine((32, 32), eps, k, dh, T, 1e-6,
                            rate_fn=flat_rate(fft_ms=1e-3))
    assert cheap_fft.method == "fft"
    # bf16: eligible only when the tier's measured floor fits inside
    # the margin; cheapest (0.7x) once it is
    loose = pick_engine((32, 32), eps, k, dh, T, 1e-4,
                        rate_fn=flat_rate())
    assert loose.precision == "bf16"
    just_tight = pick_engine((32, 32), eps, k, dh, T,
                             BF16_L2_BUDGET, rate_fn=flat_rate())
    assert just_tight.precision == "f32"
    # accuracy-CAPPED bf16: the tier's floor rides inside the budget
    # (a smaller dt), instead of the candidate being generated and then
    # unconditionally rejected by its own feasibility check
    coarse = pick_engine((32, 32), eps, k, 0.05, 30 * euler_bound(
        eps, k, 0.05), 1e-4, rate_fn=flat_rate())
    assert coarse.precision == "bf16"
    from nonlocalheatequation_tpu.serve.picker import modeled_error

    assert ERR_SAFETY * (modeled_error(2, 30 * euler_bound(eps, k, 0.05),
                                       coarse.dt)
                         + BF16_L2_BUDGET) <= 1e-4 * (1 + 1e-12)
    # wire round trip (the router frame form)
    assert EngineChoice.from_wire(ch.wire()) == ch
    # expo: opt-in only, one step, fft
    exp = pick_engine((32, 32), eps, k, dh, T, 1e-6, allow_expo=True,
                      rate_fn=flat_rate(fft_ms=1e-6))
    assert (exp.stepper, exp.steps, exp.method) == ("expo", 1, "fft")


def test_picker_env_ladder(monkeypatch):
    eps, k, dh = 2, 1.0, 0.01
    T = 30 * euler_bound(eps, k, dh)
    monkeypatch.setenv("NLHEAT_PICK_STAGES", "16")
    ch = pick_engine((32, 32), eps, k, dh, T, 1e-6, rate_fn=flat_rate())
    assert (ch.stepper, ch.stages) == ("rkc", 16)
    monkeypatch.setenv("NLHEAT_PICK_STAGES", "1,4")
    with pytest.raises(ValueError, match="NLHEAT_PICK_STAGES"):
        pick_engine((32, 32), eps, k, dh, T, 1e-6, rate_fn=flat_rate())


def test_picked_sibling_on_fused_fleet_drops_comm():
    # a comm='fused' (pallas) fleet must still serve a picked non-pallas
    # engine: the sibling drops to the collective transport instead of
    # refusing at construction (the fused family is pallas-only)
    base = EnsembleEngine(method="pallas", comm="fused")
    sib = base.engine_for("rkc", 8, "fft", "f32")
    assert (sib.method, sib.comm) == ("fft", "collective")
    # a pallas pick keeps the fleet's fused engine
    sib2 = base.engine_for("rkc", 8, "pallas", "f32")
    assert sib2.comm == "fused"
    # and a supervised pipeline classifies (not crashes on) a picked
    # engine whose construction fails outright
    with ServePipeline(method="auto", depth=1, window_ms=0.0,
                       retries=0, fallback=False) as pipe:
        h = pipe.submit(
            EnsembleCase(shape=(16, 16), nt=2, eps=2, k=1.0, dt=1e-5,
                         dh=0.05, test=True),
            engine=("expo", 0, "conv", "f32"))  # expo needs fft: refuses
        pipe.drain()
        assert h.error is not None  # quarantined, pipeline alive
        assert h.error.classification == "error"


def test_picker_served_bit_identical_to_offline_sibling():
    eps, k, dh = 2, 1.0, 0.01
    T = 30 * euler_bound(eps, k, dh)
    ch = pick_engine((24, 24), eps, k, dh, T, 1e-6,
                     rate_fn=flat_rate(fft_ms=1e9), allow_fft=True)
    assert ch.stepper == "rkc"
    cases = [EnsembleCase(shape=(24, 24), nt=ch.steps, eps=eps, k=k,
                          dt=ch.dt, dh=dh, test=True) for _ in range(3)]
    with ServePipeline(method="auto", depth=2, window_ms=0.0) as pipe:
        # a default-engine case shares the pipeline with picked ones
        h0 = pipe.submit(EnsembleCase(shape=(24, 24), nt=3, eps=eps,
                                      k=k, dt=1e-5, dh=dh, test=True))
        hs = [pipe.submit(c, engine=ch) for c in cases]
        pipe.drain()
        served = [h.result for h in hs]
        assert h0.result is not None
        # picked and default cases never share a chunk/program
        assert pipe.report.buckets == 2
    offline = EnsembleEngine(**ch.engine_kwargs()).run(cases)
    assert all(np.array_equal(a, b) for a, b in zip(served, offline, strict=True))
    # served accuracy actually meets the target the picker promised
    op = NonlocalOp2D(eps, k, ch.dt, dh)
    want = (np.cos(2.0 * np.pi * (ch.steps * ch.dt))
            * op.spatial_profile(24, 24))
    d = served[0] - want
    assert float((d * d).sum()) / (24 * 24) <= 1e-6


# ---------------------------------------------------------------------------
# the fleet: gang sharded rkc over sockets + the picked HTTP form
# ---------------------------------------------------------------------------


def test_gang_sharded_rkc_socket_and_http_picked_form():
    from nonlocalheatequation_tpu.serve.http import IngressServer
    from nonlocalheatequation_tpu.serve.router import ReplicaRouter

    eps, k, dh = 2, 1.0, 0.01
    eul = euler_bound(eps, k, dh)
    T = 30 * eul
    ch = pick_engine((24, 24), eps, k, dh, T, 1e-6, allow_fft=False)
    assert ch.stepper == "rkc"  # fine dh: super-stepping wins
    # the offline oracle: the SAME adapter the gang worker calls, with
    # the picked stepper threaded through (sat is not pallas, so fused
    # honestly falls back to collective — recorded)
    big = EnsembleCase(shape=(24, 24), nt=ch.steps, eps=eps, k=k,
                       dt=ch.dt, dh=dh, test=True)
    want_big, info = solve_case_sharded(
        big, ndevices=8, comm="fused", method="sat",
        stepper=ch.stepper, stages=ch.stages)
    assert info["stepper"] == "rkc"
    assert info["error_l2"] / (24 * 24) <= 1e-6
    with ReplicaRouter(replicas=1, method="sat", batch_sizes=(1,),
                       transport="tcp", shard_threshold=16 * 16,
                       gang_devices=8) as router:
        with IngressServer(0, router) as ing:
            def post(body):
                r = urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{ing.port}/v1/cases",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"}))
                return json.loads(r.read())

            # the picked form, small tier: engine evidence in the 202
            resp = post({"shape": [16, 16], "eps": eps, "k": k,
                         "dh": dh, "T_final": T, "accuracy": 1e-6,
                         "test": True})
            assert resp["engine"]["stepper"] == "rkc"
            assert resp["nt"] == resp["engine"]["steps"]
            # the picked form, SHARDED tier (24^2 > 16^2): the gang
            # worker honors the pick over the socket
            resp2 = post({"shape": [24, 24], "eps": eps, "k": k,
                          "dh": dh, "T_final": T, "accuracy": 1e-6,
                          "test": True})
            assert resp2["engine"]["stepper"] == "rkc"
            # the fft axis is OPEN for this (grid, mesh) pair since
            # ISSUE 16 (capability gate, not a hardcoded exclusion);
            # the analytic rates price the 24^2 stencil under it here
            assert resp2["engine"]["method"] != "fft"
            for rid in (resp["id"], resp2["id"]):
                r = urllib.request.urlopen(
                    f"http://127.0.0.1:{ing.port}/v1/cases/{rid}"
                    "?wait=1&timeout_s=300")
                assert json.loads(r.read())["status"] == "done"
            # the sharded result crosses the socket bit-identical to
            # the offline picked-stepper distributed solve
            r = urllib.request.urlopen(
                f"http://127.0.0.1:{ing.port}/v1/cases/"
                f"{resp2['id']}/result")
            body = json.loads(r.read())
            got = np.asarray(body["values"]).reshape(24, 24)
            assert np.array_equal(got, want_big)
            # an unmeetable deadline is a loud 422, never a slow solve
            with pytest.raises(urllib.error.HTTPError) as ei:
                post({"shape": [16, 16], "eps": eps, "k": k, "dh": dh,
                      "T_final": T, "accuracy": 1e-6,
                      "deadline_ms": 1e-9, "test": True})
            assert ei.value.code == 422
            assert json.loads(ei.value.read())["refused"] == "picker"
            # ambiguous (both forms at once) is the client's 400
            with pytest.raises(urllib.error.HTTPError) as ei2:
                post({"shape": [16, 16], "eps": eps, "k": k, "dh": dh,
                      "nt": 3, "dt": 1e-5, "T_final": T,
                      "accuracy": 1e-6, "test": True})
            assert ei2.value.code == 400
            # a picked-form body missing a field (or with a bad-rank
            # shape) is the client's 400 too, never a 500-shaped
            # KeyError (parse_case's contract, kept by the new form)
            for bad in ({"T_final": T, "accuracy": 1e-6},
                        {"shape": [4, 4, 4, 4], "eps": eps, "k": k,
                         "dh": dh, "T_final": T, "accuracy": 1e-6},
                        # eps=0 / dh=0 would divide the picker's
                        # stability constant by zero — client 400s
                        {"shape": [16, 16], "eps": 0, "k": k, "dh": dh,
                         "T_final": T, "accuracy": 1e-6, "test": True},
                        {"shape": [16, 16], "eps": eps, "k": k,
                         "dh": 0, "T_final": T, "accuracy": 1e-6,
                         "test": True}):
                with pytest.raises(urllib.error.HTTPError) as ei3:
                    post(bad)
                assert ei3.value.code == 400
        m = router.metrics()
        assert m["sharded_cases"] == 1
        assert router.registry.get("/router/picked-cases").value == 2


def test_gang_sharded_fft_picks_over_tcp(monkeypatch):
    # ISSUE 16: sharded picks compete over the FULL method/stepper
    # space — an fft (and a forced-expo) pick crosses HTTP -> router ->
    # gang over TCP and lands bit-identical to the offline
    # solve_case_sharded sibling on the pencil-decomposed spectral tier
    from nonlocalheatequation_tpu.serve.http import IngressServer
    from nonlocalheatequation_tpu.serve.router import ReplicaRouter

    eps, k, dh = 5, 1.0, 0.02
    T = 30 * euler_bound(eps, k, dh)
    # fft-base fleet, tight target at eps=5: the analytic model prices
    # rkc-4-on-fft under every stencil candidate — a NATURAL fft pick
    ch = pick_engine((32, 32), eps, k, dh, T, 1e-6, method="fft")
    assert (ch.stepper, ch.stages, ch.method) == ("rkc", 4, "fft")
    # and the forced-expo envelope (NLHEAT_PICK_EXPO=1) picks expo on
    # the same axis — the opt-in caller owns the interior contract
    monkeypatch.setenv("NLHEAT_PICK_EXPO", "1")
    che = pick_engine((32, 32), eps, k, dh, T, 1e-6, method="fft")
    monkeypatch.delenv("NLHEAT_PICK_EXPO")
    assert (che.stepper, che.method, che.steps) == ("expo", "fft", 1)
    # offline oracles through the SAME adapter + comm config the gang
    # worker runs: the fused gang honestly serves fft picks on the
    # collective all-to-all transposes (ValueError fallback, recorded)
    want, info = solve_case_sharded(
        EnsembleCase(shape=(32, 32), nt=ch.steps, eps=eps, k=k,
                     dt=ch.dt, dh=dh, test=True),
        ndevices=8, comm="fused", method="fft",
        stepper=ch.stepper, stages=ch.stages)
    assert info["comm"] == "collective"
    assert info["error_l2"] / (32 * 32) <= 1e-6
    wante, infoe = solve_case_sharded(
        EnsembleCase(shape=(32, 32), nt=1, eps=eps, k=k, dt=che.dt,
                     dh=dh, test=True),
        ndevices=8, comm="fused", method="fft",
        stepper="expo", stages=che.stages)
    assert infoe["comm"] == "collective"
    assert infoe["stepper"] == "expo"
    with ReplicaRouter(replicas=1, method="fft", batch_sizes=(1,),
                       transport="tcp", shard_threshold=16 * 16,
                       gang_devices=8) as router:
        with IngressServer(0, router) as ing:
            def post(body):
                r = urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{ing.port}/v1/cases",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"}))
                return json.loads(r.read())

            body = {"shape": [32, 32], "eps": eps, "k": k, "dh": dh,
                    "T_final": T, "accuracy": 1e-6, "test": True}
            resp = post(body)
            assert resp["engine"]["stepper"] == "rkc"
            assert resp["engine"]["method"] == "fft"
            monkeypatch.setenv("NLHEAT_PICK_EXPO", "1")
            respe = post(body)
            monkeypatch.delenv("NLHEAT_PICK_EXPO")
            assert respe["engine"]["stepper"] == "expo"
            assert respe["engine"]["method"] == "fft"
            for rid, want_arr in ((resp["id"], want),
                                  (respe["id"], wante)):
                r = urllib.request.urlopen(
                    f"http://127.0.0.1:{ing.port}/v1/cases/{rid}"
                    "?wait=1&timeout_s=300")
                assert json.loads(r.read())["status"] == "done"
                r = urllib.request.urlopen(
                    f"http://127.0.0.1:{ing.port}/v1/cases/{rid}"
                    "/result")
                got = np.asarray(
                    json.loads(r.read())["values"]).reshape(32, 32)
                assert np.array_equal(got, want_arr)
        assert router.metrics()["sharded_cases"] == 2


# ---------------------------------------------------------------------------
# the distributed CLIs' stepper surface
# ---------------------------------------------------------------------------


def run_cli(module, args, stdin=""):
    return subprocess.run(
        [sys.executable, "-m", f"nonlocalheatequation_tpu.cli.{module}",
         "--platform", "cpu", *args],
        input=stdin, capture_output=True, text=True, timeout=540,
        cwd=REPO, env={**os.environ})


def test_cli_distributed_stepper_surface():
    # a distributed rkc batch row passes the manufactured contract
    r = run_cli("solve2d_distributed",
                ["--test_batch", "--stepper", "rkc",
                 "--superstep-stages", "4"],
                stdin="1\n12 12 2 2 4 2 1.0 0.005 0.05\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr
    # rc-2 over-bound refusal with the bound ACTUALLY in force printed
    r2 = run_cli("solve2d_distributed",
                 ["--test", "true", "--nx", "12", "--ny", "12",
                  "--nt", "3", "--eps", "2", "--dt", "0.05",
                  "--stepper", "rkc", "--superstep-stages", "4"])
    assert r2.returncode == 2
    assert "rkc[s=4] stability bound" in r2.stderr
    assert "bound in force" in r2.stderr
    # expo without --method fft is refused on the distributed CLI too
    # (rc 1, named reason; expo + --method fft runs the sharded
    # spectral tier — tests/test_spectral_sharded.py)
    r3 = run_cli("solve2d_distributed", ["--test", "true",
                                         "--stepper", "expo"])
    assert r3.returncode == 1
    assert "requires --method fft" in r3.stderr
    # elastic + rkc is refused (the elastic executor steps with Euler)
    r4 = run_cli("solve2d_distributed",
                 ["--test", "true", "--nbalance", "5",
                  "--stepper", "rkc"])
    assert r4.returncode == 1
    assert "elastic executor" in r4.stderr


def test_cli_solve3d_distributed_rkc():
    # the 3D CLI's distributed scan now takes the stepper axis
    r = run_cli("solve3d",
                ["--test", "--distributed", "--nx", "8", "--ny", "8",
                 "--nz", "8", "--nt", "3", "--eps", "2",
                 "--dt", "0.002", "--stepper", "rkc",
                 "--superstep-stages", "4"])
    assert r.returncode == 0, r.stderr
    assert "rkc[s=4]" in r.stderr  # the bound in force, announced
    # expo + --distributed + --method fft now runs the sharded spectral
    # tier (ISSUE 16) and holds the manufactured contract
    r2 = run_cli("solve3d", ["--test", "--distributed", "--method",
                             "fft", "--stepper", "expo", "--nx", "8",
                             "--ny", "8", "--nz", "8", "--nt", "3",
                             "--eps", "2", "--cmp", "0"])
    assert r2.returncode == 0, r2.stderr
    # ... but fft + the fused stencil transport stays refused
    r3 = run_cli("solve3d", ["--test", "--distributed", "--method",
                             "fft", "--comm", "fused"])
    assert r3.returncode == 1
    assert "pencil" in r3.stderr
