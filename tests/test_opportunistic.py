"""Black-box CI tests for tools/tpu_opportunistic.sh (CPU smoke mode).

The opportunistic queue is the flaky-tunnel measurement runner: it probes
for heal windows, gates each window on a no-fallback bench, and works
through prioritized steps whose outputs must carry backend and variant/tm
evidence before rows enter the table.  These tests exercise the success
path (resident variant engages, queue completes) and the strike path (a
step that deterministically cannot produce its required label is struck
twice on a healthy backend, then skipped) — the same policy-level testing
the bench/sanity harnesses get (tests/test_bench_harness.py,
tests/test_sanity_harness.py).
"""

import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tools", "tpu_opportunistic.sh")

ALL_STEPS = [
    "bench4096", "resident512", "carried4096", "superstep2",
    "bf16-4096", "bf16-carried4096", "ensemble8x1024", "serve8x1024",
    "servefault8x1024", "obs8x1024", "multichip1024", "fft4096",
    "tta4096", "warmboot1024", "router8x1024", "routerobs8x1024",
    "sloaudit8x1024", "fleettcp8x1024", "ttafleet8x512",
    "fftgang8x4096", "session8x256", "mesh4096",
    "autotune-2d512", "autotune-2d4096", "autotune-3d256",
    "table-unstructured", "table-elastic", "table-elastic-general",
    "table-unstructured3d", "table-eps-sweep", "sanity",
    "superstep2-tm128", "superstep3-tm96", "tm160", "tm192",
    "tm224", "tm256", "stretch8192", "table-methods2d", "table-small2d",
    "table-dist2d", "table-scaling", "table-3d", "profile",
]


def _run(tmp_path, leave_undone, extra_env, timeout=560):
    state = tmp_path / "state"
    state.write_text(
        "".join(f"{s}\n" for s in ALL_STEPS if s != leave_undone))
    table = tmp_path / "table.jsonl"
    out = tmp_path / "opp.log"
    env = dict(os.environ)
    # scrub every ambient bench knob that could flip a child's behavior
    # (same hygiene as tests/test_bench_harness.py)
    for k in ("BENCH_PLATFORM", "BENCH_CARRIED", "BENCH_RESIDENT",
              "BENCH_FAULT", "BENCH_METHOD", "BENCH_GRID", "BENCH_LADDER",
              "BENCH_ACCURACY", "NLHEAT_TM", "BENCH_WARMBOOT",
              "NLHEAT_PROGRAM_STORE"):
        env.pop(k, None)
    env.update(
        OPP_GATE_BACKEND="cpu",
        OPP_STATE=str(state),
        OPP_TABLE=str(table),
        OPP_OUT=str(out),
        PROBE_INTERVAL_S="15",
        OPP_BUDGET_H="1",
        BENCH_STEPS="2",  # keep every bench child fast on CPU
    )
    env.update(extra_env)  # per-test overrides (may rewrite the defaults)
    proc = subprocess.run(
        ["bash", SCRIPT], env=env, cwd=REPO, timeout=timeout,
        capture_output=True, text=True)
    return proc, state.read_text(), table.read_text(), out.read_text()


def test_success_path_resident_variant(tmp_path):
    # interpreted pallas on CPU lets the resident kernel genuinely engage;
    # the step must record a variant-labeled row and complete the queue
    proc, state, table, _out = _run(
        tmp_path, "resident512", {"BENCH_METHOD": "pallas"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "resident512\n" in state
    assert "fail:" not in state
    assert '"variant": "resident"' in table


@pytest.mark.slow  # ~60 s (a gate bench + the chaos bench child) — the
# underlying servefault machinery is tier-1-covered by
# tests/test_bench_harness.py; this proves the queue's gating greps
def test_servefault_step_banks_chaos_evidence(tmp_path):
    # the chaos A/B step must only bank when the JSON carries the
    # servefault variant, all requests served (no poison), and a
    # genuinely engaged fallback route
    proc, state, table, _out = _run(
        tmp_path, "servefault8x1024", {"OPP_GRID_ENS": "24"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "servefault8x1024\n" in state
    assert "fail:" not in state
    assert '"variant": "servefault4"' in table
    assert '"served": 8' in table and '"poison": 0' in table
    assert '"fault_plan": "raise@1x2"' in table


@pytest.mark.slow  # ~45 s (a gate bench + the obs A/B bench child) — the
# traced-vs-untraced machinery is tier-1-covered by
# tests/test_bench_harness.py; this proves the queue's gate parses the
# overhead field and validates the trace artifact
def test_obs_step_banks_trace_evidence(tmp_path):
    # the obs A/B step must only bank when the JSON carries the serveobs
    # variant, trace_overhead <= 1.05, and a Perfetto-loadable artifact
    import json

    tdir = tmp_path / "obs_trace"
    proc, state, table, _out = _run(
        tmp_path, "obs8x1024",
        # the overhead threshold is opened up: a millisecond-scale CPU
        # proxy under CI load measures timer noise, not tracing cost (the
        # CPU-proxy overhead evidence is the bench_table obs group's
        # job); this test proves the gate's STRUCTURE — variant label,
        # overhead field parsed, artifact validated — banks the step
        {"OPP_GRID_ENS": "24", "OPP_OBS_TRACE_DIR": str(tdir),
         "OPP_OBS_MAX_OVERHEAD": "10"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "obs8x1024\n" in state
    assert "fail:" not in state
    assert '"variant": "serveobs4"' in table
    assert '"trace_overhead"' in table and '"spans"' in table
    doc = json.loads((tdir / "host_trace.json").read_text())
    assert doc["traceEvents"], "trace artifact empty"


def test_multichip_step_banks_halo_ab_evidence(tmp_path):
    # the fused-vs-collective halo A/B step (round 9) must only bank
    # when the JSON carries the multichip variant, the halo_overlap
    # ratio, and the fused comm label; on the 8-virtual-device CPU
    # smoke mesh the A/B runs the real shard_map programs
    proc, state, table, _out = _run(
        tmp_path, "multichip1024", {"OPP_GRID_MC": "64"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "multichip1024\n" in state
    assert "fail:" not in state
    assert '"variant": "multichip8"' in table
    assert '"halo_overlap"' in table
    assert '"comm": "fused"' in table


@pytest.mark.slow  # ~45 s (a gate bench + the tta search child) — the
# tta machinery itself is tier-1-covered by tests/test_bench_harness.py;
# this proves the queue's gate parses steps_ratio + the winner's
# met_target before banking
def test_tta_step_banks_steps_to_solution_evidence(tmp_path):
    proc, state, table, _out = _run(
        tmp_path, "tta4096",
        # the >= 10x acceptance ratio is a large-grid property; the tiny
        # CPU smoke grid proves the gate structure with a relaxed limit
        {"OPP_GRID_TTA": "64", "OPP_TTA_MIN_RATIO": "2"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "tta4096\n" in state
    assert "fail:" not in state
    assert '"variant": "tta"' in table
    assert '"steps_ratio"' in table and '"tta"' in table


@pytest.mark.slow  # ~45 s (a gate bench + the warmboot A/B child) — the
# cold/warm machinery is tier-1-covered by tests/test_bench_harness.py
# and tests/test_program_store.py; this proves the queue's gate parses
# the speedup/hit/bit-identity fields before banking
def test_warmboot_step_banks_store_evidence(tmp_path):
    store_dir = tmp_path / "program_store"
    proc, state, table, _out = _run(
        tmp_path, "warmboot1024",
        # the >= 2x ratio is real on CPU too (compile >> load), but a
        # millisecond-scale proxy under CI load is noisy — keep the
        # structural gate tight on fields, relaxed on the ratio
        {"OPP_GRID_ENS": "24", "OPP_WB_DIR": str(store_dir),
         "OPP_WB_MIN_SPEEDUP": "1.2"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "warmboot1024\n" in state
    assert "fail:" not in state
    assert '"variant": "warmboot"' in table
    assert '"warmboot_speedup"' in table
    assert '"store_hits": 1' in table
    assert '"bit_identical": true' in table
    # the persistent store dir holds the serialized executable the next
    # heal window will reuse
    assert list(store_dir.glob("*.aotprog"))


@pytest.mark.slow  # ~60 s (a gate bench + the router fleet child, which
# spawns worker processes) — the fleet machinery is tier-1-covered by
# tests/test_router.py and tests/test_bench_harness.py; this proves the
# queue's gate parses speedup/shed/bit-identity before banking, and that
# the step's deliberate cpu-labeled rows pass its backend exemption
def test_router_step_banks_fleet_evidence(tmp_path):
    proc, state, table, _out = _run(
        tmp_path, "router8x1024",
        # tiny-grid CPU smoke: 2 replicas (17 worker spawns would eat
        # the CI budget), a step floor that keeps per-case compute above
        # the submit cost so the burst point genuinely sheds, and the
        # speedup gate relaxed to structure (the 2.5x acceptance is the
        # calibrated 256^2 proxy, docs/round12.md)
        {"OPP_ROUTER_REPLICAS": "2", "OPP_GRID_ROUTER": "32",
         "BENCH_ROUTER_STEPS": "600",
         "OPP_ROUTER_MIN_SPEEDUP": "0.1"}, timeout=560)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "router8x1024\n" in state
    assert "fail:" not in state
    assert '"variant": "router2"' in table
    assert '"router_speedup"' in table
    assert '"load_sweep"' in table
    assert '"bit_identical": true' in table


@pytest.mark.slow  # ~60 s (a gate bench + the traced fleet child) — the
# fleet-tracing machinery is tier-1-covered by tests/test_trace_fleet.py;
# this proves the queue's gate parses overhead/merged-trace/steady-state
# fields, validates the merged Perfetto artifact spans >= 2 processes,
# and that the step's deliberate cpu-labeled rows pass its exemption
def test_routerobs_step_banks_fleet_trace_evidence(tmp_path):
    import json

    tdir = tmp_path / "fleet_trace"
    proc, state, table, _out = _run(
        tmp_path, "routerobs8x1024",
        # tiny-grid CPU smoke (2 replicas, relaxed overhead limit — a
        # millisecond-scale proxy under CI load measures timer noise;
        # the structural gate stays tight: merged artifact, >= 2 pids,
        # steady_state_builds == 0, bit_identical)
        {"OPP_ROUTER_REPLICAS": "2", "OPP_GRID_ROUTER": "32",
         "BENCH_ROUTER_STEPS": "600",
         "OPP_ROUTEROBS_TRACE_DIR": str(tdir),
         "OPP_ROUTEROBS_MAX_OVERHEAD": "10"}, timeout=560)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "routerobs8x1024\n" in state
    assert "fail:" not in state
    assert '"variant": "routerobs2"' in table
    assert '"trace_overhead"' in table
    assert '"steady_state_builds": 0' in table
    assert '"bit_identical": true' in table
    doc = json.loads((tdir / "fleet_trace.json").read_text())
    assert len({e.get("pid") for e in doc["traceEvents"]}) >= 2


@pytest.mark.slow  # ~90 s (a gate bench + the pipe/TCP fleet child with
# a gang replica) — the transport + sharded-tier machinery is tier-1-
# covered by tests/test_fleet_tcp.py and test_bench_harness; this proves
# the queue's gate parses tcp_overhead/sharded_cases/shed/bit-identity
# before banking, and the step's cpu-labeled rows pass its exemption
def test_fleettcp_step_banks_transport_evidence(tmp_path):
    proc, state, table, _out = _run(
        tmp_path, "fleettcp8x1024",
        # tiny-grid CPU smoke: 2 replicas + a 2-device gang mesh, the
        # shared step floor, and the overhead limit relaxed to
        # structure (a millisecond-scale proxy under CI load measures
        # timer noise, not the socket hop)
        {"OPP_ROUTER_REPLICAS": "2", "OPP_GRID_ROUTER": "32",
         "BENCH_ROUTER_STEPS": "600", "BENCH_FLEET_CASES": "8",
         "BENCH_FLEET_SHARDED": "1", "BENCH_FLEET_GANG": "2",
         "OPP_FLEETTCP_MAX_OVERHEAD": "10"}, timeout=560)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "fleettcp8x1024\n" in state
    assert "fail:" not in state
    assert '"variant": "fleettcp2"' in table
    assert '"tcp_overhead"' in table
    assert '"sharded_cases"' in table
    assert '"bit_identical": true' in table


@pytest.mark.slow  # ~60 s (a gate bench + the fleet-TTA child with a
# gang replica) — the stepper/picker machinery itself is tier-1-covered
# by tests/test_distributed_rkc.py and test_bench_harness; this proves
# the queue's gate parses steps_ratio/met_target/bit_identical before
# banking, and the step's cpu-labeled rows pass the backend-grep
# exemption like router8x1024
def test_ttafleet_step_banks_picker_evidence(tmp_path):
    proc, state, table, _out = _run(
        tmp_path, "ttafleet8x512",
        # tiny-grid smoke: eps 2 at 32^2 puts the accuracy-capped dt
        # well past the Euler bound, so the picker genuinely picks rkc
        # and the >= 10x steps_ratio floor holds even at smoke scale
        {"OPP_GRID_TTAFLEET": "32", "BENCH_EPS": "2",
         "BENCH_STEPS": "20", "BENCH_FLEET_GANG": "2"}, timeout=560)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "ttafleet8x512\n" in state
    assert "fail:" not in state
    assert '"variant": "ttafleet"' in table
    assert '"picker_engine"' in table
    assert '"met_target": true' in table
    assert '"bit_identical": true' in table


@pytest.mark.slow  # ~60 s (a gate bench + the spectral A/B fleet child;
# a worker process hosts the 2-device gang mesh) — the sharded-fft
# machinery itself is tier-1-covered by tests/test_spectral_sharded.py
# and test_distributed_rkc.py; this proves the queue's gate parses
# steps_ratio/met_target/bit_identical before banking, and the step's
# cpu-labeled rows pass the backend-grep exemption like router8x1024
def test_fftgang_step_banks_spectral_evidence(tmp_path):
    proc, state, table, _out = _run(
        tmp_path, "fftgang8x4096",
        # tiny-grid smoke: eps 3 at 64^2 with 40 Euler steps — the
        # accuracy-capped dt sits well past the Euler bound, so the
        # picker's fft-axis engine lands at 1 step and the >= 10x
        # steps_ratio floor holds even at smoke scale
        {"OPP_GRID_FFTGANG": "64", "OPP_FFTGANG_DEVICES": "2",
         "BENCH_EPS": "3", "BENCH_STEPS": "40"}, timeout=560)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "fftgang8x4096\n" in state
    assert "fail:" not in state
    assert '"variant": "fftgang2"' in table
    assert '"picker_engine"' in table
    assert '"met_target": true' in table
    assert '"bit_identical": true' in table
    assert '"sharded"' in table  # the gang's comm/mesh recorded


@pytest.mark.slow  # ~60 s (a gate bench + the mesh A/B child) — the
# gather-engine machinery itself is tier-1-covered by
# tests/test_pallas_gather.py and tests/test_unstructured.py; this
# proves the queue's gate parses points_ratio/met_target/bit_identical/
# warm_zero_built before banking, and the step's deliberately
# cpu-labeled rows pass the backend-grep exemption like router8x1024
def test_mesh_step_banks_gather_evidence(tmp_path):
    proc, state, table, _out = _run(
        tmp_path, "mesh4096",
        # the 64^2 smoke grid is the same calibration the bench rung was
        # designed at: the graded 32x32 cloud resolves the manufactured
        # solution with exactly 4x fewer points, so the real >= 4
        # points_ratio floor holds unrelaxed even at smoke scale
        {"OPP_GRID_MESH": "64"}, timeout=560)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    assert "mesh4096\n" in state
    assert "fail:" not in state
    assert '"variant": "mesh"' in table
    assert '"points_ratio"' in table
    assert '"met_target": true' in table
    assert '"bit_identical": true' in table
    assert '"warm_zero_built": true' in table
    assert '"mesh_hash"' in table  # the registry key the evidence cites


@pytest.mark.slow  # ~73 s: two strike rounds, each a full bench child plus
# a re-gate bench — the queue's success path above stays in the tier-1
# budget; run `pytest -m slow tests/test_opportunistic.py` for this one
def test_strike_path_unlabelable_step(tmp_path):
    # with the sat method the artifact can never carry a "tm" label, and
    # the backend stays healthy, so the step must strike twice (classified
    # deterministic by the post-failure re-gate) and then be skipped
    proc, state, table, out = _run(
        tmp_path, "tm160",
        {"BENCH_METHOD": "sat", "OPP_GRID_LARGE": "256"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "queue complete" in proc.stdout
    lines = state.splitlines()
    assert lines.count("fail:tm160") == 2
    assert "tm160" not in lines  # struck out, never marked done
    assert '"tm": 160' not in table  # no mislabeled/unlabeled row landed
    assert "strike 2/2" in out
