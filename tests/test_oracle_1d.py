"""1D oracle: the reference's Test_1d batch cases (CMakeLists.txt:101)."""

import pytest

from tests.cases import CASES_1D, L2_THRESHOLD

from nonlocalheatequation_tpu.models.solver1d import Solver1D
from nonlocalheatequation_tpu.ops.constants import c_1d


@pytest.mark.parametrize("nx,nt,eps,k,dt,dx", CASES_1D)
def test_batch_case_oracle(nx, nt, eps, k, dt, dx):
    s = Solver1D(nx, nt, eps, k=k, dt=dt, dx=dx, backend="oracle")
    s.test_init()
    s.do_work()
    assert s.error_l2 / nx <= L2_THRESHOLD


def test_c1d_truncates_like_reference():
    # src/1d_nonlocal_serial.cpp:57 declares c_1d as long: (k*3)/pow(eps*dx,3)
    # truncates.  k=0.5,eps=40,dx=0.02 -> 1.5/0.512 = 2.92... -> 2
    assert c_1d(0.5, 40, 0.02) == 2.0
    # k=0.02,eps=40,dx=0.01 -> 0.06/0.064 = 0.9375 -> 0 (tests/1d.txt row 9)
    assert c_1d(0.02, 40, 0.01) == 0.0
    assert c_1d(1.0, 5, 0.02) == 2999.0 or c_1d(1.0, 5, 0.02) == 3000.0


def test_jit_matches_oracle():
    nx, nt, eps, k, dt, dx = CASES_1D[0]
    a = Solver1D(nx, nt, eps, k=k, dt=dt, dx=dx, backend="oracle")
    b = Solver1D(nx, nt, eps, k=k, dt=dt, dx=dx, backend="jit")
    a.test_init()
    b.test_init()
    ua, ub = a.do_work(), b.do_work()
    assert abs(ua - ub).max() < 1e-12
