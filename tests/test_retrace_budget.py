"""Retrace-budget regression (ISSUE 14 satellite): the number of
programs a ServePipeline round-trip builds is a RECORDED budget, and a
warm round-trip builds ZERO more.

graftlint's K1 proves the program KEY is complete; this test pins the
complementary dynamic invariant the linter cannot see — that no argument
silently went static->dynamic (which would show up as extra traces for
the same case set) and that the per-engine program cache actually serves
the second round-trip.  ``EnsembleReport.programs_built`` counts exactly
the traced-and-compiled programs (a store hit counts under
``programs_loaded`` instead, serve/ensemble.py build_program), so the
budget reads straight off the report the pipeline already keeps.

If an intentional change alters how chunks map to programs, update
COLD_BUDGET with the new arithmetic in the comment — the point is that
the number moves only when someone MEANS it to.
"""

import numpy as np

from nonlocalheatequation_tpu.serve.ensemble import EnsembleCase
from nonlocalheatequation_tpu.serve.server import ServePipeline

#: Two buckets (16x16 and 12x12, same nt/eps/test), four cases each.
#: Each bucket closes as ONE padded chunk of size 4 -> one program per
#: bucket.  The physics tuple is uniform per bucket, so re-submitting
#: the same shapes/physics must re-use both programs byte-for-byte.
COLD_BUDGET = 2

NT, EPS = 2, 2


def _round_trip(pipe, rng):
    cases = []
    for shape in ((16, 16), (12, 12)):
        for _ in range(4):
            cases.append(EnsembleCase(
                shape=shape, nt=NT, eps=EPS, k=1.0, dt=1e-4, dh=0.02,
                test=False, u0=rng.normal(size=shape)))
    handles = [pipe.submit(c) for c in cases]
    pipe.drain()
    return np.stack([np.asarray(h.result).ravel()[:4] for h in handles])


def test_warm_round_trip_stays_at_recorded_budget():
    rng = np.random.default_rng(7)
    with ServePipeline(depth=1, window_ms=10_000.0) as pipe:
        first = _round_trip(pipe, rng)
        assert pipe.report.programs_built == COLD_BUDGET, (
            "cold round-trip built a different number of programs than "
            "the recorded budget — a static arg went dynamic (extra "
            "traces) or chunking changed (fewer/more); if intentional, "
            "re-derive COLD_BUDGET")
        second = _round_trip(pipe, rng)
        assert pipe.report.programs_built == COLD_BUDGET, (
            "warm round-trip RETRACED: the same buckets/physics must "
            "hit the per-engine program cache with zero new builds")
        assert pipe.report.programs_loaded == 0  # no store configured
        # same programs, fresh inputs: results exist and are finite
        assert np.isfinite(first).all() and np.isfinite(second).all()


def test_warm_budget_holds_across_interleaved_buckets():
    """Interleaved submission order must not mint extra programs: the
    bucket key, not arrival order, decides program identity."""
    rng = np.random.default_rng(11)
    with ServePipeline(depth=1, window_ms=10_000.0) as pipe:
        shapes = [(16, 16), (12, 12)] * 4  # strict interleave
        for shape in shapes:
            pipe.submit(EnsembleCase(
                shape=shape, nt=NT, eps=EPS, k=1.0, dt=1e-4, dh=0.02,
                test=False, u0=rng.normal(size=shape)))
        pipe.drain()
        assert pipe.report.programs_built == COLD_BUDGET
