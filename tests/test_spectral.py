"""Spectral fast-operator path + stepper tier (ISSUE 8).

Contracts pinned here:
* the baked rfftn symbol equals the literal cosine sum per (eps, grid)
  (ops/spectral.symbol_direct) — the symbol identity;
* ``method='fft'`` applies are <= 1e-12 of the pallas oracle (1D: the
  shift oracle — no 1D pallas kernel exists) on small f64 grids;
* the manufactured-solution contract ``error_l2/#points <= 1e-6`` holds
  for every shipped (method, stepper) combination at configs inside each
  integrator's accuracy envelope (expo's boundary-coupling model:
  models/steppers.py docstring);
* RKC refuses loudly at dt just past its stability model and runs
  UNCHANGED on the pallas path (stage loop above the method dispatch);
* expo is fft-only with a loud refusal elsewhere, and over-resolved
  Euler converges first-order TO the expo answer on a boundary-clear
  state (the exactness demonstration);
* stepper/method join the ensemble engine key; fft cases served through
  the PR 3 pipeline are bit-identical to the offline engine.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from nonlocalheatequation_tpu.models import steppers
from nonlocalheatequation_tpu.models.solver1d import Solver1D
from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.models.solver3d import Solver3D
from nonlocalheatequation_tpu.ops import spectral
from nonlocalheatequation_tpu.ops.constants import (
    rkc_beta,
    stable_dt,
    stable_dt_op,
)
from nonlocalheatequation_tpu.ops.nonlocal_op import (
    NonlocalOp1D,
    NonlocalOp2D,
    NonlocalOp3D,
)


# --------------------------------------------------------------------------
# symbol identity + apply oracle
# --------------------------------------------------------------------------


@pytest.mark.parametrize("eps,shape", [
    (2, (17,)), (5, (50,)),
    (2, (12, 18)), (5, (30, 30)), (9, (24, 40)),
    (2, (10, 12, 14)), (3, (16, 16, 16)),
])
def test_symbol_matches_direct_cosine_sum(eps, shape):
    from nonlocalheatequation_tpu.ops.stencil import (
        horizon_mask_1d,
        horizon_mask_2d,
        horizon_mask_3d,
        influence_weights,
    )

    mask = {1: horizon_mask_1d, 2: horizon_mask_2d,
            3: horizon_mask_3d}[len(shape)](eps)
    w = influence_weights(mask, None, 0.02)
    box = spectral.fft_box(shape, eps)
    baked = spectral.neighbor_symbol(w, box)
    direct = spectral.symbol_direct(w, box)
    assert baked.shape == direct.shape
    assert np.abs(baked - direct).max() <= 1e-11 * max(1.0, w.sum())


def test_operator_symbol_nonpositive_zero_at_dc():
    op = NonlocalOp2D(4, 1.0, 1e-4, 0.02, method="fft")
    lam = spectral.operator_symbol(op, (24, 24))
    assert lam.flat[0] == pytest.approx(0.0, abs=1e-7)
    assert lam.max() <= 1e-7  # <= 0 up to symbol rounding


@pytest.mark.parametrize("dim,eps,shape", [
    (1, 5, (50,)), (1, 3, (31,)),
    (2, 4, (24, 24)), (2, 9, (20, 28)),
    (3, 3, (12, 12, 12)),
])
def test_fft_apply_matches_oracle_1e12(dim, eps, shape):
    """fft vs the pallas oracle (2D/3D; interpret-mode on the CPU suite)
    and the shift/NumPy oracles, <= 1e-12 relative on f64."""
    mk = {1: NonlocalOp1D, 2: NonlocalOp2D, 3: NonlocalOp3D}[dim]
    h = 1.0 / shape[0]
    op_fft = mk(eps, 1.0, 1e-5, h, method="fft")
    u = np.random.default_rng(dim).normal(size=shape)
    got = np.asarray(op_fft.apply(jnp.asarray(u)))
    want_np = op_fft.apply_np(u)
    scale = max(1.0, np.abs(want_np).max())
    assert np.abs(got - want_np).max() / scale <= 1e-12
    if dim in (2, 3):
        op_pl = mk(eps, 1.0, 1e-5, h, method="pallas")
        want_pl = np.asarray(op_pl.apply(jnp.asarray(u)))
        assert np.abs(got - want_pl).max() / scale <= 1e-12


def test_fft_refuses_padded_blocks():
    op = NonlocalOp2D(3, 1.0, 1e-4, 0.02, method="fft")
    with pytest.raises(ValueError, match="whole-domain"):
        op.neighbor_sum_padded(jnp.zeros((20, 20)))
    op3 = NonlocalOp3D(2, 1.0, 1e-4, 0.05, method="fft")
    with pytest.raises(ValueError, match="whole-domain"):
        op3.neighbor_sum_padded(jnp.zeros((12, 12, 12)))


def test_fft_box_is_5smooth_and_padded():
    for n, eps in [(50, 5), (511, 8), (4096, 8), (13, 2)]:
        (b,) = spectral.fft_box((n,), eps)
        assert b >= n + eps
        x = b
        for p in (2, 3, 5):
            while x % p == 0:
                x //= p
        assert x == 1, f"box {b} not 5-smooth"


# --------------------------------------------------------------------------
# stability model (the ISSUE 8 bugfix: stable_dt is the single source)
# --------------------------------------------------------------------------


def test_stable_dt_model():
    op = NonlocalOp2D(5, 1.0, 1.0, 0.02)
    euler = stable_dt_op(op, "euler")
    assert euler == pytest.approx(1.0 / (op.c * op.dh ** 2 * op.wsum))
    # rkc interval ~2 s^2 (damped slightly below), monotonic in s
    assert rkc_beta(2) == pytest.approx(2 * 4, rel=0.05)
    assert rkc_beta(10) == pytest.approx(2 * 100, rel=0.05)
    assert rkc_beta(5) < rkc_beta(6)
    assert stable_dt_op(op, "rkc", 8) == pytest.approx(
        euler * rkc_beta(8) / 2.0)
    assert stable_dt_op(op, "expo") == np.inf
    # the reference's truncated-to-zero 1D constant: empty spectrum
    assert stable_dt(0.0, 0.01, 1, 81.0) == np.inf
    with pytest.raises(ValueError):
        stable_dt(1.0, 0.02, 2, 81.0, stepper="leapfrog")


def test_rkc_refuses_dt_past_model():
    op = NonlocalOp2D(5, 1.0, 1.0, 0.02)
    bound = stable_dt_op(op, "rkc", 4)
    bad = NonlocalOp2D(5, 1.0, bound * 1.01, 0.02)
    with pytest.raises(ValueError, match="RKC stability bound"):
        steppers.validate_stepper(bad, "rkc", 4)
    ok = NonlocalOp2D(5, 1.0, bound * 0.99, 0.02)
    steppers.validate_stepper(ok, "rkc", 4)  # just inside: accepted
    with pytest.raises(ValueError, match="stages >= 2"):
        steppers.validate_stepper(ok, "rkc", 1)


def test_expo_requires_fft():
    op = NonlocalOp2D(5, 1.0, 1e-4, 0.02, method="conv")
    with pytest.raises(ValueError, match="method='fft'"):
        steppers.validate_stepper(op, "expo")
    with pytest.raises(ValueError, match="Euler-only"):
        Solver2D(20, 20, 5, 3, backend="oracle", stepper="rkc", stages=4)


# --------------------------------------------------------------------------
# manufactured-solution gate for every (method, stepper) pair
# --------------------------------------------------------------------------


@pytest.mark.parametrize("method,stepper,stages", [
    ("conv", "euler", 0), ("sat", "euler", 0), ("fft", "euler", 0),
    ("pallas", "rkc", 4), ("conv", "rkc", 8), ("fft", "rkc", 8),
])
def test_manufactured_gate_2d(method, stepper, stages):
    """The reference batch config (50^2, eps 5, nt 45) for every
    (method, stepper) pair; rkc-on-pallas is the no-kernel-edits claim
    (the stage loop sits above the method dispatch)."""
    s = Solver2D(50, 50, 45, 5, k=1.0, dt=0.0005, dh=0.02, backend="jit",
                 method=method, stepper=stepper, stages=stages)
    s.test_init()
    s.do_work()
    assert s.error_l2 / (50 * 50) <= 1e-6, (method, stepper, s.error_l2)


def test_manufactured_gate_2d_expo():
    """expo gated inside its accuracy envelope: the boundary-coupling
    defect scales ~(dt*lambda_max)^2 * |u|_boundary per step
    (models/steppers.py docstring), so the gate config keeps
    dt at 0.25x the Euler bound; the super-stepping exactness story is
    the boundary-clear Richardson test below."""
    op0 = NonlocalOp2D(5, 1.0, 1.0, 1.0 / 128)
    dt = 0.25 * stable_dt_op(op0, "euler")
    s = Solver2D(128, 128, 45, 5, k=1.0, dt=dt, dh=1.0 / 128,
                 backend="jit", method="fft", stepper="expo")
    s.test_init()
    s.do_work()
    assert s.error_l2 / (128 * 128) <= 1e-6, s.error_l2


@pytest.mark.parametrize("method,stepper,stages", [
    ("shift", "euler", 0), ("fft", "euler", 0), ("fft", "rkc", 8),
    ("shift", "rkc", 4),
])
def test_manufactured_gate_1d(method, stepper, stages):
    s = Solver1D(50, 45, 5, k=1.0, dt=0.001, dx=0.02, backend="jit",
                 method=method, stepper=stepper, stages=stages)
    s.test_init()
    s.do_work()
    assert s.error_l2 / 50 <= 1e-6, (method, stepper, s.error_l2)


@pytest.mark.parametrize("method,stepper,stages", [
    ("sat", "euler", 0), ("fft", "euler", 0), ("fft", "rkc", 4),
])
def test_manufactured_gate_3d(method, stepper, stages):
    s = Solver3D(16, 16, 16, 20, 3, k=1.0, dt=0.0005, dh=0.0625,
                 backend="jit", method=method, stepper=stepper,
                 stages=stages)
    s.test_init()
    s.do_work()
    assert s.error_l2 / 16 ** 3 <= 1e-6, (method, stepper, s.error_l2)


def test_rkc_superstep_past_euler_bound():
    """The point of the tier: the SAME horizon in 9x fewer steps at dt
    9x the reference's (past the Euler bound), inside the contract."""
    # reference: 45 steps at dt=5e-4; rkc: 5 steps at dt=4.5e-3
    s = Solver2D(50, 50, 5, 5, k=1.0, dt=0.0045, dh=0.02, backend="jit",
                 method="conv", stepper="rkc", stages=8)
    s.test_init()
    s.do_work()
    assert s.error_l2 / (50 * 50) <= 1e-6, s.error_l2


# --------------------------------------------------------------------------
# expo exactness (boundary-clear state)
# --------------------------------------------------------------------------


def test_expo_exact_limit_of_euler():
    """On a state that stays clear of the boundary, over-resolved Euler
    converges FIRST-ORDER to the one-giant-step expo answer — i.e. expo
    is the exact dt->0 limit (the spectral-exactness demonstration; the
    step is 24x the Euler bound)."""
    n, eps = 128, 3
    h = 1.0 / n
    T = 24 * stable_dt_op(NonlocalOp1D(eps, 1.0, 1.0, h), "euler")
    x = np.arange(n)
    u0 = np.exp(-((x - n / 2) ** 2) / (2 * 4.0 ** 2))
    op_x = NonlocalOp1D(eps, 1.0, T, h, method="fft")
    e1 = np.asarray(steppers.make_multi_step_fn(
        op_x, 1, dtype=jnp.float64, stepper="expo")(jnp.asarray(u0), 0))
    errs = []
    for N in (250, 500, 1000):
        op_eu = NonlocalOp1D(eps, 1.0, T / N, h)
        eu = np.asarray(steppers.make_multi_step_fn(
            op_eu, N, dtype=jnp.float64)(jnp.asarray(u0), 0))
        errs.append(np.abs(e1 - eu).max())
    # halving dt halves the distance to expo => expo is the limit
    assert errs[0] / errs[1] == pytest.approx(2.0, rel=0.02)
    assert errs[1] / errs[2] == pytest.approx(2.0, rel=0.02)


def test_expo_one_step_any_horizon_unconditionally_stable():
    """A dt 200x past the Euler bound: Euler diverges violently, expo
    stays bounded and decays (lambda <= 0 end to end)."""
    n, eps = 64, 4
    h = 1.0 / n
    dt_e = stable_dt_op(NonlocalOp1D(eps, 1.0, 1.0, h), "euler")
    op = NonlocalOp1D(eps, 1.0, 200 * dt_e, h, method="fft")
    u0 = np.random.default_rng(0).normal(size=n)
    out = np.asarray(steppers.make_multi_step_fn(
        op, 3, dtype=jnp.float64, stepper="expo")(jnp.asarray(u0), 0))
    assert np.all(np.isfinite(out))
    assert np.abs(out).max() <= np.abs(u0).max() * 1.01


# --------------------------------------------------------------------------
# ensemble / serve integration
# --------------------------------------------------------------------------


def _cases(k=1.0):
    from nonlocalheatequation_tpu.serve.ensemble import EnsembleCase

    return [EnsembleCase(shape=(24, 24), nt=10, eps=3, k=k, dt=2e-4,
                         dh=1.0 / 24, test=True) for _ in range(3)]


def test_stepper_joins_ensemble_engine_key():
    from nonlocalheatequation_tpu.serve.ensemble import EnsembleEngine

    e1 = EnsembleEngine(method="fft", stepper="rkc", stages=4)
    e1.run(_cases())
    e2 = EnsembleEngine(method="fft", stepper="euler")
    e2.run(_cases())
    k1 = next(iter(e1._programs))
    k2 = next(iter(e2._programs))
    assert k1 != k2 and "rkc" in k1 and "euler" in k2
    assert e1.report.strategies[_cases()[0].bucket_key()] == "stacked[rkc]"
    # sibling carries the stepper (the CPU-fallback twin must solve the
    # same integrator, and an expo sibling must keep method='fft')
    sib = e1.sibling()
    assert sib.stepper == "rkc" and sib.stages == 4
    from nonlocalheatequation_tpu.serve.resilience import CpuFallback

    fb = CpuFallback(EnsembleEngine(method="fft", stepper="expo"))
    assert fb._sibling(2).method == "fft"
    assert fb._sibling(2).stepper == "expo"


def test_ensemble_stepper_matches_sequential_bitwise():
    """A stepper bucket's stacked program is the per-case solo stepper
    scan inlined — bit-identical to sequential solves by construction."""
    from nonlocalheatequation_tpu.serve.ensemble import EnsembleEngine

    cases = _cases()
    states = EnsembleEngine(method="fft", stepper="rkc", stages=4).run(cases)
    for case, got in zip(cases, states, strict=True):
        op = NonlocalOp2D(case.eps, case.k, case.dt, case.dh, method="fft")
        g, lg = op.source_parts(*case.shape)
        solo = steppers.make_multi_step_fn(
            op, case.nt, g, lg, jnp.float64, stepper="rkc", stages=4)
        want = np.asarray(solo(jnp.asarray(op.spatial_profile(*case.shape),
                                           jnp.float64), 0))
        assert np.array_equal(np.asarray(got), want)


def test_engine_refuses_euler_only_variants_for_steppers():
    from nonlocalheatequation_tpu.serve.ensemble import EnsembleEngine

    for variant in ("carried", "superstep", "vmap"):
        with pytest.raises(ValueError, match="Euler-only"):
            EnsembleEngine(method="pallas", stepper="rkc", stages=4,
                           variant=variant,
                           ksteps=2 if variant == "superstep" else 0)
    with pytest.raises(ValueError, match="method='fft'"):
        EnsembleEngine(method="conv", stepper="expo")
    with pytest.raises(ValueError, match="stages"):
        EnsembleEngine(method="conv", stepper="rkc")


def test_serve_fft_cases_bit_identical_to_offline():
    """fft cases through the PR 3 pipeline == offline run() bitwise
    (same programs, different schedule) — serving serves the spectral
    tier on the existing machinery."""
    from nonlocalheatequation_tpu.serve.ensemble import EnsembleEngine
    from nonlocalheatequation_tpu.serve.server import ServePipeline

    cases = _cases()
    offline = EnsembleEngine(method="fft", stepper="rkc", stages=4).run(cases)
    engine = EnsembleEngine(method="fft", stepper="rkc", stages=4)
    with ServePipeline(engine=engine, depth=2, window_ms=0.0) as pipe:
        handles = [pipe.submit(c) for c in cases]
        pipe.drain()
    for h, want in zip(handles, offline, strict=True):
        assert h.error is None
        assert np.array_equal(np.asarray(h.result), np.asarray(want))


# --------------------------------------------------------------------------
# obs wiring + autotune method dimension
# --------------------------------------------------------------------------


def test_stepper_obs_gauges_and_span():
    from nonlocalheatequation_tpu.obs import trace as obs_trace
    from nonlocalheatequation_tpu.obs.metrics import REGISTRY

    op = NonlocalOp2D(3, 1.0, 1e-4, 1.0 / 24, method="fft")
    before = REGISTRY.counter("/op/fft-applies").snapshot()
    tracer = obs_trace.Tracer()
    prev = obs_trace.set_tracer(tracer)
    try:
        multi = steppers.make_multi_step_fn(op, 4, dtype=jnp.float64,
                                            stepper="rkc", stages=4)
        multi(jnp.zeros((24, 24)), 0)
    finally:
        obs_trace.set_tracer(prev)
    assert REGISTRY.gauge("/stepper/stages").snapshot() == 4
    assert REGISTRY.gauge("/stepper/eff-dt").snapshot() == \
        pytest.approx(1e-4)
    assert REGISTRY.counter("/op/fft-applies").snapshot() > before
    names = [ev["name"] for ev in tracer.chrome_trace()["traceEvents"]]
    assert "stepper.superstep" in names


def test_tune_method_picks_and_runs(monkeypatch, tmp_path):
    """NLHEAT_TUNE_METHOD=1: the stencil<->fft crossover probes both and
    the chosen program still computes the same function (<= 1e-12)."""
    monkeypatch.setenv("NLHEAT_TUNE_METHOD", "1")
    monkeypatch.setenv("NLHEAT_AUTOTUNE_CACHE",
                       str(tmp_path / "tune.json"))
    from nonlocalheatequation_tpu.utils import autotune

    autotune._memory_cache.clear()
    op = NonlocalOp2D(9, 1.0, 1e-5, 1.0 / 32, method="conv")
    u0 = np.random.default_rng(3).normal(size=(32, 32))
    multi = steppers.make_multi_step_fn(op, 6, dtype=jnp.float64)
    got = np.asarray(multi(jnp.asarray(u0), 0))
    monkeypatch.delenv("NLHEAT_TUNE_METHOD")
    base = steppers.make_multi_step_fn(op, 6, dtype=jnp.float64)
    want = np.asarray(base(jnp.asarray(u0), 0))
    assert np.abs(got - want).max() <= 1e-12 * max(1.0, np.abs(want).max())
    # the probe banked a method-ab record with both candidates timed
    entry = next((v for k, v in autotune._memory_cache.items()
                  if "method-ab" in k), None)
    assert entry is not None
    assert set(entry["ms_per_step"]) >= {"conv", "fft"}
