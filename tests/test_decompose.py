"""Decomposition toolchain tests — the C8 analog (SURVEY.md section 3.4).

Covers: structured .msh generation + parsing, dh/size inference (the
reference's recipe, domain_decomposition.cpp:99-121), RCB partitioning
(native and NumPy paths agree; balanced; contiguous), the nparts<2 bypass,
divisibility validation, the CLI surface (flags and stdin modes), and the
partition-map round trip into mesh placement.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from nonlocalheatequation_tpu.utils import decompose as dc
from nonlocalheatequation_tpu.utils.gmsh import read_msh, write_structured_msh
from nonlocalheatequation_tpu.utils.partition_map import read_partition_map


@pytest.fixture
def msh_20x10(tmp_path):
    path = str(tmp_path / "20x10.msh")
    write_structured_msh(path, 20, 10, 0.05)
    return path


def test_msh_roundtrip_and_inference(msh_20x10):
    msh = read_msh(msh_20x10)
    assert msh.quads.shape == (200, 4)
    assert msh.coords.shape == (231, 3)
    mx, my, dh = dc.infer_structured_grid(msh)
    assert (mx, my) == (20, 10)
    assert dh == pytest.approx(0.05)


def test_quad_corner_coords_consistent(msh_20x10):
    qc = read_msh(msh_20x10).quad_coords()
    # every quad is an axis-aligned dh x dh square, corners ordered like
    # GMSH's (first two nodes differ in y)
    for q in qc[:5]:
        assert q[1, 1] - q[0, 1] == pytest.approx(0.05)
        assert q[3, 0] - q[0, 0] == pytest.approx(0.05)


def test_partition_balanced_and_contiguous():
    a = dc.partition_coarse_grid(8, 8, 4)
    counts = np.bincount(a.ravel(), minlength=4)
    assert counts.max() - counts.min() <= 1
    # contiguity: each part's tiles form one 4-connected component
    for p in range(4):
        tiles = {(int(x), int(y)) for x, y in zip(*np.nonzero(a == p))}
        seen = set()
        stack = [next(iter(tiles))]
        while stack:
            t = stack.pop()
            if t in seen or t not in tiles:
                continue
            seen.add(t)
            x, y = t
            stack += [(x + 1, y), (x - 1, y), (x, y + 1), (x, y - 1)]
        assert seen == tiles, f"part {p} is not contiguous"


def test_partition_single_node_bypass():
    # reference: METIS FPE workaround, all tiles -> locality 0
    assert (dc.partition_coarse_grid(5, 5, 1) == 0).all()


def test_numpy_fallback_matches_native():
    if dc._native_lib is None:
        pytest.skip("native partition library not built")
    ids = np.arange(6 * 4)
    xy = np.stack([(ids % 6) + 0.5, (ids // 6) + 0.5], 1).astype(np.float64)
    np_parts = dc.rcb_numpy(xy, 4)
    nat = np.zeros(24, dtype=np.int32)
    assert dc._native_lib.partition_rcb(24, np.ascontiguousarray(xy), 4, nat) == 0
    assert (np_parts == nat).all()


def test_decompose_divisibility_error(msh_20x10):
    with pytest.raises(ValueError, match="not divisible"):
        dc.decompose(msh_20x10, 2, 3, 5)


def test_decompose_pipeline(msh_20x10, tmp_path):
    pmap = dc.decompose(msh_20x10, 4, coarse_x=5, coarse_y=5)
    assert (pmap.npx, pmap.npy) == (4, 2)
    assert (pmap.nx, pmap.ny) == (5, 5)
    assert pmap.dh == pytest.approx(0.05)
    assert pmap.num_owners == 4


def test_cli_flags_mode(msh_20x10, tmp_path):
    out = str(tmp_path / "map.txt")
    r = subprocess.run(
        [sys.executable, "-m", "nonlocalheatequation_tpu.cli.decompose",
         msh_20x10, out, "2", "--sx", "5", "--sy", "5"],
        capture_output=True, text=True, check=True)
    assert "x dimension : 20" in r.stdout
    pmap = read_partition_map(out)
    assert (pmap.npx, pmap.npy) == (4, 2)
    counts = np.bincount(pmap.assignment.ravel(), minlength=2)
    assert counts.max() - counts.min() <= 1


def test_cli_stdin_mode(msh_20x10, tmp_path):
    out = str(tmp_path / "map.txt")
    r = subprocess.run(
        [sys.executable, "-m", "nonlocalheatequation_tpu.cli.decompose",
         msh_20x10, out, "1"],
        input="5 5\n", capture_output=True, text=True, check=True)
    assert "Enter coarse mesh size" in r.stdout
    pmap = read_partition_map(out)
    assert (pmap.assignment == 0).all()


def test_cli_one_flag_prompts_for_other(msh_20x10, tmp_path):
    out = str(tmp_path / "map.txt")
    r = subprocess.run(
        [sys.executable, "-m", "nonlocalheatequation_tpu.cli.decompose",
         msh_20x10, out, "2", "--sx", "5"],
        input="5\n", capture_output=True, text=True, check=True)
    # only the missing size is prompted for; --sx 5 is kept
    assert "along y-dimension" in r.stdout
    assert "along x-dimension" not in r.stdout
    pmap = read_partition_map(out)
    assert (pmap.nx, pmap.ny) == (5, 5)
    assert (pmap.npx, pmap.npy) == (4, 2)


def test_cli_bad_divisor_exits_zero(msh_20x10, tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "nonlocalheatequation_tpu.cli.decompose",
         msh_20x10, str(tmp_path / "map.txt"), "2", "--sx", "3", "--sy", "5"],
        capture_output=True, text=True)
    assert r.returncode == 0
    assert "not divisible" in r.stdout


# -- shipped data fixtures (the reference's data/ meshes, README.md:20) ------
def test_shipped_data_pipeline(tmp_path):
    """data/10x10.msh -> decompose -> distributed solve with the map file."""
    root = os.path.join(os.path.dirname(__file__), "..")
    msh = os.path.join(root, "data", "10x10.msh")
    if not os.path.exists(msh):
        pytest.skip("data/ fixtures not generated (tools/gen_data.py)")
    out = str(tmp_path / "map.txt")
    from nonlocalheatequation_tpu.utils.partition_map import write_partition_map

    write_partition_map(out, dc.decompose(msh, 4, 5, 5))
    pmap = read_partition_map(out)
    assert (pmap.npx, pmap.npy) == (2, 2)
    assert sorted(np.unique(pmap.assignment)) == [0, 1, 2, 3]

    from nonlocalheatequation_tpu.parallel.elastic import ElasticSolver2D

    s = ElasticSolver2D(pmap.nx, pmap.ny, pmap.npx, pmap.npy, nt=5, eps=2,
                        k=1.0, dt=1e-4, dh=pmap.dh,
                        assignment=pmap.assignment)
    s.test_init()
    s.do_work()
    from tests.cases import L2_THRESHOLD

    assert s.error_l2 / (pmap.nx * pmap.npx * pmap.ny * pmap.npy) <= L2_THRESHOLD


@pytest.mark.parametrize("npx,npy,nparts", [(2, 2, 4), (3, 3, 4), (5, 5, 4),
                                            (4, 2, 8), (5, 5, 2)])
def test_partition_all_parts_present_and_balanced(npx, npy, nparts):
    """Regression: refine_cut must never empty a part (it used to merge
    singleton parts away, e.g. 2x2 into 4 -> owners {1,3})."""
    if dc._native_lib is None:
        # the NumPy fallback never runs refine_cut; this test would pass
        # vacuously
        pytest.skip("native partition library not built (refine_cut untested)")
    a = dc.partition_coarse_grid(npx, npy, nparts)
    counts = np.bincount(a.ravel(), minlength=nparts)
    assert (counts > 0).all(), counts
    n = npx * npy
    assert counts.min() >= n // nparts
    assert counts.max() <= n // nparts + 1
