"""Decomposition toolchain tests — the C8 analog (SURVEY.md section 3.4).

Covers: structured .msh generation + parsing, dh/size inference (the
reference's recipe, domain_decomposition.cpp:99-121), RCB partitioning
(native and NumPy paths agree; balanced; contiguous), the nparts<2 bypass,
divisibility validation, the CLI surface (flags and stdin modes), and the
partition-map round trip into mesh placement.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from nonlocalheatequation_tpu.utils import decompose as dc
from nonlocalheatequation_tpu.utils.gmsh import read_msh, write_structured_msh
from nonlocalheatequation_tpu.utils.partition_map import read_partition_map


@pytest.fixture
def msh_20x10(tmp_path):
    path = str(tmp_path / "20x10.msh")
    write_structured_msh(path, 20, 10, 0.05)
    return path


def test_msh_roundtrip_and_inference(msh_20x10):
    msh = read_msh(msh_20x10)
    assert msh.quads.shape == (200, 4)
    assert msh.coords.shape == (231, 3)
    mx, my, dh = dc.infer_structured_grid(msh)
    assert (mx, my) == (20, 10)
    assert dh == pytest.approx(0.05)


def test_quad_corner_coords_consistent(msh_20x10):
    qc = read_msh(msh_20x10).quad_coords()
    # every quad is an axis-aligned dh x dh square, corners ordered like
    # GMSH's (first two nodes differ in y)
    for q in qc[:5]:
        assert q[1, 1] - q[0, 1] == pytest.approx(0.05)
        assert q[3, 0] - q[0, 0] == pytest.approx(0.05)


def test_partition_balanced_and_contiguous():
    a = dc.partition_coarse_grid(8, 8, 4)
    counts = np.bincount(a.ravel(), minlength=4)
    assert counts.max() - counts.min() <= 1
    # contiguity: each part's tiles form one 4-connected component
    for p in range(4):
        tiles = {(int(x), int(y)) for x, y in zip(*np.nonzero(a == p), strict=True)}
        seen = set()
        stack = [next(iter(tiles))]
        while stack:
            t = stack.pop()
            if t in seen or t not in tiles:
                continue
            seen.add(t)
            x, y = t
            stack += [(x + 1, y), (x - 1, y), (x, y + 1), (x, y - 1)]
        assert seen == tiles, f"part {p} is not contiguous"


def test_partition_single_node_bypass():
    # reference: METIS FPE workaround, all tiles -> locality 0
    assert (dc.partition_coarse_grid(5, 5, 1) == 0).all()


def test_numpy_fallback_matches_native():
    if dc._native_lib is None:
        pytest.skip("native partition library not built")
    ids = np.arange(6 * 4)
    xy = np.stack([(ids % 6) + 0.5, (ids // 6) + 0.5], 1).astype(np.float64)
    np_parts = dc.rcb_numpy(xy, 4)
    nat = np.zeros(24, dtype=np.int32)
    assert dc._native_lib.partition_rcb(24, np.ascontiguousarray(xy), 4, nat) == 0
    assert (np_parts == nat).all()


def test_decompose_divisibility_error(msh_20x10):
    with pytest.raises(ValueError, match="not divisible"):
        dc.decompose(msh_20x10, 2, 3, 5)


def test_decompose_pipeline(msh_20x10, tmp_path):
    pmap = dc.decompose(msh_20x10, 4, coarse_x=5, coarse_y=5)
    assert (pmap.npx, pmap.npy) == (4, 2)
    assert (pmap.nx, pmap.ny) == (5, 5)
    assert pmap.dh == pytest.approx(0.05)
    assert pmap.num_owners == 4


def test_cli_flags_mode(msh_20x10, tmp_path):
    out = str(tmp_path / "map.txt")
    r = subprocess.run(
        [sys.executable, "-m", "nonlocalheatequation_tpu.cli.decompose",
         msh_20x10, out, "2", "--sx", "5", "--sy", "5"],
        capture_output=True, text=True, check=True)
    assert "x dimension : 20" in r.stdout
    pmap = read_partition_map(out)
    assert (pmap.npx, pmap.npy) == (4, 2)
    counts = np.bincount(pmap.assignment.ravel(), minlength=2)
    assert counts.max() - counts.min() <= 1


def test_cli_stdin_mode(msh_20x10, tmp_path):
    out = str(tmp_path / "map.txt")
    r = subprocess.run(
        [sys.executable, "-m", "nonlocalheatequation_tpu.cli.decompose",
         msh_20x10, out, "1"],
        input="5 5\n", capture_output=True, text=True, check=True)
    assert "Enter coarse mesh size" in r.stdout
    pmap = read_partition_map(out)
    assert (pmap.assignment == 0).all()


def test_cli_one_flag_prompts_for_other(msh_20x10, tmp_path):
    out = str(tmp_path / "map.txt")
    r = subprocess.run(
        [sys.executable, "-m", "nonlocalheatequation_tpu.cli.decompose",
         msh_20x10, out, "2", "--sx", "5"],
        input="5\n", capture_output=True, text=True, check=True)
    # only the missing size is prompted for; --sx 5 is kept
    assert "along y-dimension" in r.stdout
    assert "along x-dimension" not in r.stdout
    pmap = read_partition_map(out)
    assert (pmap.nx, pmap.ny) == (5, 5)
    assert (pmap.npx, pmap.npy) == (4, 2)


def test_cli_bad_divisor_exits_zero(msh_20x10, tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "nonlocalheatequation_tpu.cli.decompose",
         msh_20x10, str(tmp_path / "map.txt"), "2", "--sx", "3", "--sy", "5"],
        capture_output=True, text=True)
    assert r.returncode == 0
    assert "not divisible" in r.stdout


# -- shipped data fixtures (the reference's data/ meshes, README.md:20) ------
def test_shipped_data_pipeline(tmp_path):
    """data/10x10.msh -> decompose -> distributed solve with the map file."""
    root = os.path.join(os.path.dirname(__file__), "..")
    msh = os.path.join(root, "data", "10x10.msh")
    if not os.path.exists(msh):
        pytest.skip("data/ fixtures not generated (tools/gen_data.py)")
    out = str(tmp_path / "map.txt")
    from nonlocalheatequation_tpu.utils.partition_map import write_partition_map

    write_partition_map(out, dc.decompose(msh, 4, 5, 5))
    pmap = read_partition_map(out)
    assert (pmap.npx, pmap.npy) == (2, 2)
    assert sorted(np.unique(pmap.assignment)) == [0, 1, 2, 3]

    from nonlocalheatequation_tpu.parallel.elastic import ElasticSolver2D

    s = ElasticSolver2D(pmap.nx, pmap.ny, pmap.npx, pmap.npy, nt=5, eps=2,
                        k=1.0, dt=1e-4, dh=pmap.dh,
                        assignment=pmap.assignment)
    s.test_init()
    s.do_work()
    from tests.cases import L2_THRESHOLD

    assert s.error_l2 / (pmap.nx * pmap.npx * pmap.ny * pmap.npy) <= L2_THRESHOLD


@pytest.mark.parametrize("npx,npy,nparts", [(2, 2, 4), (3, 3, 4), (5, 5, 4),
                                            (4, 2, 8), (5, 5, 2)])
def test_partition_all_parts_present_and_balanced(npx, npy, nparts):
    """Regression: refine_cut must never empty a part (it used to merge
    singleton parts away, e.g. 2x2 into 4 -> owners {1,3}).  No native
    gate anymore: the NumPy fallback runs refine_cut_numpy, so both
    paths exercise the donor guard."""
    a = dc.partition_coarse_grid(npx, npy, nparts)
    counts = np.bincount(a.ravel(), minlength=nparts)
    assert (counts > 0).all(), counts
    n = npx * npy
    assert counts.min() >= n // nparts
    assert counts.max() <= n // nparts + 1


# ---------------------------------------------------------------------------
# Edge-cut quality vs the dual-graph optimum (VERDICT r3 C8 gap): the
# reference minimizes this via METIS_PartMeshDual
# (domain_decomposition.cpp:185-187); the native RCB+refine must land at or
# near the optimum, not just claim "equivalent capability".
# ---------------------------------------------------------------------------


def _stripe_cut(n):
    # straight-line bisection of an n x n grid under 8-neighbor adjacency:
    # n direct + 2(n-1) diagonal cut pairs
    return 3 * n - 2


def test_edge_cut_counts_eight_neighbor_pairs():
    a = np.zeros((4, 4), dtype=int)
    a[2:] = 1
    assert dc.edge_cut(a) == _stripe_cut(4)
    assert dc.edge_cut(np.zeros((5, 5), int)) == 0
    # checkerboard cuts every DIRECT pair (2*n*(n-1)) but no diagonal pair
    # (diagonal neighbors share parity)
    n = 4
    cb = np.fromfunction(lambda x, y: (x + y) % 2, (n, n), dtype=int)
    assert dc.edge_cut(cb) == 2 * n * (n - 1)


def test_bisection_matches_brute_force_optimum():
    # 4x4 grid, 2 balanced parts: enumerate all C(16,8) = 12870 balanced
    # bipartitions for the TRUE dual-graph optimum
    from itertools import combinations

    n = 4
    best = 10 ** 9
    for ones in combinations(range(n * n), n * n // 2):
        a = np.zeros(n * n, dtype=int)
        a[list(ones)] = 1
        best = min(best, dc.edge_cut(a.reshape(n, n)))
    got = dc.edge_cut(dc.partition_coarse_grid(n, n, 2))
    assert got == best, f"RCB+refine cut {got} vs optimum {best}"


@pytest.mark.parametrize("n,k", [(8, 2), (8, 4), (10, 4), (20, 8)])
def test_cut_at_most_block_layout(n, k):
    # the natural block layouts (stripes for 2, quadrant grid for square k)
    # are the hand-optimal references; RCB+refine must not exceed them
    parts = dc.partition_coarse_grid(n, n, k)
    counts = np.bincount(parts.ravel(), minlength=k)
    assert counts.max() - counts.min() <= 1  # balance first (METIS contract)
    if k == 2:
        ref = np.zeros((n, n), int)
        ref[n // 2:] = 1
    else:
        kk = int(np.sqrt(k))
        if kk * kk == k and n % kk == 0:
            ref = (np.arange(n)[:, None] // (n // kk)) * kk \
                + (np.arange(n)[None, :] // (n // kk))
        else:
            ref = (np.arange(n)[:, None] * 0
                   + np.minimum(np.arange(n) * k // n, k - 1)[None, :])
        ref = np.asarray(ref, int)
    assert dc.edge_cut(parts) <= dc.edge_cut(ref), (
        f"cut {dc.edge_cut(parts)} exceeds block layout {dc.edge_cut(ref)}")


def test_cut_quality_on_shipped_meshes():
    # the reference's own fixtures end-to-end: infer the structured grid,
    # partition a 5x5 coarse grid into 4, compare against the quadrant cut.
    # Root-caused r7: this failed (27 > 24+2) whenever native/ was not
    # built — the NumPy fallback ran raw RCB with NO refinement pass.
    # refine_cut_numpy (the exact port of the native move/swap passes)
    # closes that: both paths now land cut 26, which a balanced-partition
    # search shows is the {7,6,6,6} optimum under 8-neighbor adjacency
    # (the 24-cut quadrant reference is UNbalanced, 9/6/6/4 — the +2
    # margin is exactly the measured balance premium)
    data = os.path.join(os.path.dirname(__file__), "..", "data")
    for name in ("10x10.msh", "50x50.msh", "100x100.msh"):
        path = os.path.join(data, name)
        if not os.path.exists(path):
            pytest.skip("data/ fixtures not generated (tools/gen_data.py)")
        msh = read_msh(path)
        mx, my, _dh = dc.infer_structured_grid(msh)
        npx = npy = 5
        assert mx % npx == 0 and my % npy == 0
        parts = dc.partition_coarse_grid(npx, npy, 4)
        counts = np.bincount(parts.ravel(), minlength=4)
        assert counts.max() - counts.min() <= 1
        quad = (np.arange(npx)[:, None] // 3) * 2 + (np.arange(npy)[None, :] // 3)
        assert dc.edge_cut(parts) <= dc.edge_cut(np.asarray(quad, int)) + 2


@pytest.mark.parametrize("refine", ["native", "numpy"])
def test_refine_pass_improves_a_bad_start(refine):
    if refine == "native" and dc._native_lib is None:
        pytest.skip("native partition library not built")
    # interleaved stripes: balanced but maximally cut; refine must improve
    n, k = 8, 2
    parts = (np.arange(n * n) % k).astype(np.int32)
    xadj, adj = dc.dual_graph_csr(n, n)
    before = dc.edge_cut(parts.reshape(n, n))
    if refine == "native":
        dc._native_lib.refine_cut(n * n, xadj, adj, k, parts, 8)
    else:
        dc.refine_cut_numpy(xadj, adj, k, parts, 8)
    after = dc.edge_cut(parts.reshape(n, n))
    assert after < before
    counts = np.bincount(parts, minlength=k)
    assert counts.max() - counts.min() <= 1


def test_refine_numpy_matches_native_exactly():
    # refine_cut_numpy claims bit-for-bit the native iteration order and
    # tie-breaks; prove it on RCB starts AND on adversarial (interleaved)
    # starts where the swap phase does real work
    if dc._native_lib is None:
        pytest.skip("native partition library not built")
    for npx, npy, k in [(5, 5, 4), (4, 4, 2), (8, 8, 4), (10, 10, 4),
                        (20, 20, 8), (6, 4, 3), (7, 5, 4)]:
        n = npx * npy
        ids = np.arange(n)
        xy = np.stack([(ids % npx) + 0.5, (ids // npx) + 0.5],
                      1).astype(np.float64)
        start = np.zeros(n, dtype=np.int32)
        assert dc._native_lib.partition_rcb(
            n, np.ascontiguousarray(xy), k, start) == 0
        stripes = (np.arange(n) % k).astype(np.int32)
        xadj, adj = dc.dual_graph_csr(npx, npy)
        for s in (start, stripes):
            p_nat, p_np = s.copy(), s.copy()
            m_nat = dc._native_lib.refine_cut(n, xadj, adj, k, p_nat, 8)
            m_np = dc.refine_cut_numpy(xadj, adj, k, p_np, 8)
            assert m_nat == m_np
            assert np.array_equal(p_nat, p_np), (npx, npy, k)


# -- binary .msh (VERDICT r3 C8 gap: the reference's GMSH API linkage also
# accepts binary meshes, domain_decomposition.cpp:68-70) ---------------------


def test_binary_msh_round_trip(tmp_path):
    a_path = str(tmp_path / "a.msh")
    b_path = str(tmp_path / "b.msh")
    write_structured_msh(a_path, 7, 5, 0.1)
    write_structured_msh(b_path, 7, 5, 0.1, binary=True)
    a, b = read_msh(a_path), read_msh(b_path)
    assert np.array_equal(a.node_tags, b.node_tags)
    assert np.allclose(a.coords, b.coords)
    assert np.array_equal(a.quads, b.quads)


def test_binary_msh_feeds_the_decomposition_pipeline(tmp_path):
    path = str(tmp_path / "bin.msh")
    write_structured_msh(path, 10, 10, 0.1, binary=True)
    msh = read_msh(path)
    mx, my, dh = dc.infer_structured_grid(msh)
    assert (mx, my) == (10, 10)
    assert dh == pytest.approx(0.1)
    pmap = dc.decompose(msh, 4, 5, 5)
    assert sorted(np.unique(pmap.assignment)) == [0, 1, 2, 3]


def test_binary_legacy_22_rejected_with_named_error(tmp_path):
    path = tmp_path / "legacy.msh"
    path.write_bytes(b"$MeshFormat\n2.2 1 8\n"
                     + (1).to_bytes(4, "little") + b"\n$EndMeshFormat\n")
    with pytest.raises(ValueError, match="binary .msh only supported"):
        read_msh(str(path))


def test_truncated_binary_msh_rejected(tmp_path):
    src = tmp_path / "full.msh"
    write_structured_msh(str(src), 6, 6, 0.1, binary=True)
    data = src.read_bytes()
    trunc = tmp_path / "trunc.msh"
    trunc.write_bytes(data[: len(data) // 2])
    with pytest.raises(ValueError):
        read_msh(str(trunc))


def test_binary_msh_data_size_4(tmp_path):
    # 32-bit GMSH builds write size_t as 4 bytes; synthesize one by
    # rewriting the shipped writer's output structure at dsize=4
    import struct

    mx = my = 4
    nnx = mx + 1
    nnodes, nquads = nnx * nnx, mx * my
    u4 = lambda *v: struct.pack(f"<{len(v)}I", *v)  # noqa: E731
    i4 = lambda *v: struct.pack(f"<{len(v)}i", *v)  # noqa: E731
    path = tmp_path / "ds4.msh"
    with open(path, "wb") as f:
        f.write(b"$MeshFormat\n4.1 1 4\n" + struct.pack("<i", 1)
                + b"\n$EndMeshFormat\n$Nodes\n")
        f.write(u4(1, nnodes, 1, nnodes) + i4(2, 1, 0) + u4(nnodes))
        f.write(np.arange(1, nnodes + 1, dtype="<u4").tobytes())
        xyz = np.zeros((nnodes, 3))
        jj, ii = np.divmod(np.arange(nnodes), nnx)
        xyz[:, 0], xyz[:, 1] = ii * 0.1, jj * 0.1
        f.write(xyz.astype("<f8").tobytes() + b"\n$EndNodes\n$Elements\n")
        f.write(u4(1, nquads, 1, nquads) + i4(2, 1, 3) + u4(nquads))
        rows = np.empty((nquads, 5), np.uint32)
        q = np.arange(nquads)
        j, i = np.divmod(q, mx)
        n0 = j * nnx + i + 1
        rows[:, 0], rows[:, 1], rows[:, 2] = q + 1, n0, n0 + nnx
        rows[:, 3], rows[:, 4] = n0 + nnx + 1, n0 + 1
        f.write(rows.astype("<u4").tobytes() + b"\n$EndElements\n")
    msh = read_msh(str(path))
    assert msh.coords.shape == (nnodes, 3)
    assert msh.quads.shape == (nquads, 4)
    mx2, my2, dh = dc.infer_structured_grid(msh)
    assert (mx2, my2) == (mx, my) and dh == pytest.approx(0.1)


def test_binary_msh_bad_data_size_named_error(tmp_path):
    import struct

    path = tmp_path / "ds2.msh"
    path.write_bytes(b"$MeshFormat\n4.1 1 2\n" + struct.pack("<i", 1)
                     + b"\n$EndMeshFormat\n")
    with pytest.raises(ValueError, match="data-size"):
        read_msh(str(path))


def test_reference_400x400_run_config(tmp_path):
    """The reference DOCUMENTS a 4-node 400x400 / 20x20-tile run
    (README.md:61-67) but its repo cannot ship the mesh
    (.MISSING_LARGE_BLOBS).  Binary 4.1 makes it generatable and
    drivable end-to-end here: mesh -> decompose into 20x20 tiles over 4
    owners -> partition map round trip."""
    path = str(tmp_path / "400x400.msh")
    write_structured_msh(path, 400, 400, 1.0 / 400, binary=True)
    msh = read_msh(path)
    mx, my, dh = dc.infer_structured_grid(msh)
    assert (mx, my) == (400, 400)
    assert dh == pytest.approx(1.0 / 400)
    pmap = dc.decompose(msh, 4, 20, 20)
    assert (pmap.npx, pmap.npy) == (20, 20)
    counts = np.bincount(pmap.assignment.ravel(), minlength=4)
    assert counts.max() - counts.min() <= 1
    quad = (np.arange(20)[:, None] // 10) * 2 + (np.arange(20)[None, :] // 10)
    assert dc.edge_cut(pmap.assignment) <= dc.edge_cut(np.asarray(quad, int))
