"""Chaos suite: fault-tolerant serving (serve/server.py supervision,
serve/resilience.py, utils/faults.py), on the CPU/f64 suite with NO real
TPU — every fault is injected deterministically by a plan
(utils/faults.py grammar), every breaker transition is driven by an
injected clock, and every assertion reads ``ServeReport.metrics()``.

What these tests pin:

* the plan grammar parses/refuses loudly, and ``NLHEAT_FAULT_PLAN``
  reaches a default-constructed pipeline;
* TABLE-DRIVEN fault classification: each injected fault kind (raise /
  stall / NaN corruption) maps to its classification ("error" / "hang" /
  "corrupt"), its retry count, and its final request outcome — for both
  the fenced (D=1) and pipelined (D>1) schedules;
* bounded retry with exponential backoff (injected sleep records the
  delays; backoff_ms_total matches);
* poison-case quarantine by BISECTION: a persistent case-targeted fault
  in an 8-case chunk is isolated in O(log B) splits; exactly that case's
  ``wait()`` raises the typed ServeError, every chunk-mate is re-bucketed
  and served BIT-IDENTICALLY to the offline engine;
* the circuit breaker's full lifecycle — closed -> open on K consecutive
  device failures -> fallback-routed chunks while open -> half-open probe
  after the cooldown (injected clock) -> closed — observed from the
  metrics' timestamped transition trail;
* the end-to-end chaos acceptance: under a mid-stream plan (raise,
  stall, NaN at staggered dispatch indices + one persistent poison
  case), every non-poison request returns a result bit-identical to an
  uninjected offline ``EnsembleEngine.run()``, exactly the poison case
  raises ServeError, and the breaker opens, probes half-open, and
  re-closes;
* the happy path is untouched: with no faults the supervised defaults
  report all-zero failure telemetry (the schedule itself is pinned by
  tests/test_serve.py's spy counters).
"""

import numpy as np
import pytest

from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)
from nonlocalheatequation_tpu.serve.resilience import (
    CircuitBreaker,
    ServeError,
)
from nonlocalheatequation_tpu.serve.server import ServePipeline
from nonlocalheatequation_tpu.utils.faults import FaultPlan

NX, NY, EPS, NSTEPS = 16, 16, 2, 2
MIXED = [(1.0, 1e-4, 0.02), (0.5, 2e-4, 0.02), (0.2, 1e-4, 0.01)]


def _cases(n, rng, shape=(NX, NY), nt=NSTEPS):
    out = []
    for i in range(n):
        k, dt, dh = MIXED[i % len(MIXED)]
        out.append(EnsembleCase(shape=shape, nt=nt, eps=EPS, k=k, dt=dt,
                                dh=dh, test=False,
                                u0=rng.normal(size=shape)))
    return out


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# -- plan grammar ----------------------------------------------------------
def test_plan_parses_targets_counts_and_log():
    plan = FaultPlan.parse("raise@1,stall@3x2,nan@c5x*")
    kinds = [e.kind for e in plan.entries]
    assert kinds == ["raise", "stall", "nan"]
    assert plan.entries[0].attempt == 1 and plan.entries[0].left == 1
    assert plan.entries[1].attempt == 3 and plan.entries[1].left == 2
    assert plan.entries[2].case == 5 and plan.entries[2].left == float("inf")
    fired = plan.draw([0])  # attempt 0: nothing matches
    assert not fired.any()
    fired = plan.draw([5])  # attempt 1: raise@1 AND nan@c5 both match
    assert fired.raise_ is not None and fired.nan is not None
    assert [f["kind"] for f in plan.fired_log] == ["raise", "nan"]


@pytest.mark.parametrize("bad", [
    "raise", "boom@1", "nan@c", "stall@1x0", "raise@", "", "nan@cx*",
])
def test_plan_refuses_bad_specs_loudly(bad):
    with pytest.raises(ValueError, match="fault.plan|entries"):
        FaultPlan.parse(bad)


def test_attempt_targeted_count_fires_on_consecutive_attempts():
    # the xN count on an attempt-targeted entry is a RANGE: raise@1x2
    # must fire at attempts 1 AND 2 (a global attempt index passes
    # exactly once, so "the same index twice" would be unsatisfiable) —
    # with a depth-1 schedule that is an attempt and its immediate retry
    plan = FaultPlan.parse("raise@1x2")
    assert [plan.draw([0]).raise_ is not None for _ in range(4)] == \
        [False, True, True, False]
    rng = np.random.default_rng(11)
    cases = _cases(2, rng)
    with ServePipeline(depth=1, window_ms=0.0, batch_sizes=(1,),
                       retries=2, backoff_ms=0.0, fallback=False,
                       faults=FaultPlan.parse("raise@1x2")) as pipe:
        handles = [pipe.submit(c) for c in cases]
        pipe.drain()
    # case 1's first attempt (attempt 1) and its retry (attempt 2) both
    # raise; the second retry serves — two retries, two errors, no poison
    assert all(h.result is not None for h in handles)
    m = pipe.metrics()["resilience"]
    assert m["faults"] == {"error": 2}
    assert m["retries"] == 2 and m["quarantined"] == []
    # the request still carries its queue wait even though its chunk's
    # FIRST attempt died in the dispatch stage (recorded at the first
    # attempt that actually staged)
    assert all(h.queue_wait_s is not None for h in handles)


def test_env_plan_reaches_default_pipeline(monkeypatch):
    monkeypatch.setenv("NLHEAT_FAULT_PLAN", "raise@0")
    rng = np.random.default_rng(0)
    with ServePipeline(depth=1, window_ms=0.0, batch_sizes=(1,),
                       backoff_ms=0.0) as pipe:
        h = pipe.submit(_cases(1, rng)[0])
        out = h.wait()  # injected failure, retried, served
    assert out is not None
    assert pipe.metrics()["resilience"]["faults"] == {"error": 1}


# -- table-driven classification (the satellite's table) -------------------
#    (spec pattern, fetch deadline, expected classification)
FAULT_TABLE = [
    ("raise@{t}", None, "error"),
    ("stall@{t}", 60.0, "hang"),
    ("nan@{t}", None, "corrupt"),
]


@pytest.mark.parametrize("depth", [1, 3])
@pytest.mark.parametrize("spec,deadline,cls", FAULT_TABLE)
def test_transient_fault_classified_retried_and_served(depth, spec,
                                                       deadline, cls):
    # one fault firing once at the first dispatch: classified, retried
    # exactly once, and the request still serves bit-identically
    rng = np.random.default_rng(1)
    cases = _cases(1, rng)
    offline = EnsembleEngine(batch_sizes=(1,)).run(cases)
    engine = EnsembleEngine(batch_sizes=(1,))
    with ServePipeline(engine=engine, depth=depth, window_ms=0.0,
                       retries=2, backoff_ms=0.0, fallback=False,
                       fetch_deadline_ms=deadline,
                       faults=FaultPlan.parse(spec.format(t=0))) as pipe:
        h = pipe.submit(cases[0])
        out = h.wait()
    m = pipe.metrics()["resilience"]
    assert m["faults"] == {cls: 1}
    assert m["retries"] == 1
    assert m["quarantined"] == []
    assert np.array_equal(out, offline[0])


@pytest.mark.parametrize("depth", [1, 3])
@pytest.mark.parametrize("spec,deadline,cls", FAULT_TABLE)
def test_persistent_fault_exhausts_retries_and_quarantines(depth, spec,
                                                           deadline, cls):
    # the same fault made persistent and case-targeted: the retry budget
    # (2) is spent, the single-case chunk quarantines, wait() raises the
    # typed error, and chunk-MATES in the stream are unaffected
    rng = np.random.default_rng(2)
    cases = _cases(4, rng)
    offline = EnsembleEngine(batch_sizes=(1,)).run(cases)
    engine = EnsembleEngine(batch_sizes=(1,))
    with ServePipeline(engine=engine, depth=depth, window_ms=0.0,
                       retries=2, backoff_ms=0.0, fallback=False,
                       fetch_deadline_ms=deadline,
                       faults=FaultPlan.parse(spec.format(t="c2x*"))) as pipe:
        handles = [pipe.submit(c) for c in cases]
        pipe.drain()
        with pytest.raises(ServeError) as ei:
            handles[2].wait()
    err = ei.value
    assert err.classification == cls
    assert err.case_seq == 2 and err.attempts == 3
    m = pipe.metrics()["resilience"]
    assert m["faults"] == {cls: 3}
    assert m["retries"] == 2
    assert m["quarantined"] == [
        {"case": 2, "classification": cls, "attempts": 3,
         "chunk": err.chunk_id}]
    for i in (0, 1, 3):
        assert np.array_equal(handles[i].result, offline[i])


def test_hang_classification_releases_only_its_own_stall():
    # found live by the verify drive: classifying one chunk's hang used
    # to release EVERY armed stall, so a deadline tripped by a genuinely
    # slow fence defused faults on other in-flight chunks and the
    # injected outcome depended on interleaving.  Two chunks in flight,
    # both stall-armed: chunk A's transient hang must leave chunk B's
    # persistent stall armed — B still quarantines, A still serves.
    rng = np.random.default_rng(10)
    cases = _cases(2, rng)
    with ServePipeline(depth=2, window_ms=0.0, batch_sizes=(1,),
                       retries=1, backoff_ms=0.0, fallback=False,
                       fetch_deadline_ms=60.0,
                       faults=FaultPlan.parse(
                           "stall@0,stall@c1x*")) as pipe:
        ha = pipe.submit(cases[0])
        hb = pipe.submit(cases[1])
        pipe.drain()
    assert ha.result is not None and ha.error is None
    assert hb.error is not None
    assert hb.error.classification == "hang"
    m = pipe.metrics()["resilience"]
    assert [q["case"] for q in m["quarantined"]] == [1]


def test_exponential_backoff_recorded_and_slept():
    slept = []
    rng = np.random.default_rng(3)
    with ServePipeline(depth=1, window_ms=0.0, batch_sizes=(1,),
                       retries=2, backoff_ms=100.0, fallback=False,
                       faults=FaultPlan.parse("raise@c0x*"),
                       sleep=slept.append) as pipe:
        h = pipe.submit(_cases(1, rng)[0])
        pipe.drain()
    assert h.error is not None
    assert slept == [0.1, 0.2]  # backoff_ms * 2^(attempt-1), exhaustion sleeps nothing
    assert pipe.metrics()["resilience"]["backoff_ms_total"] == 300.0


def test_corrupt_results_never_open_the_breaker():
    # a persistent NaN is DATA-shaped (a divergent input reproduces on
    # any backend): it must quarantine through the normal retry/bisect
    # path WITHOUT opening the breaker — otherwise one bad input row
    # reroutes every healthy chunk to the CPU fallback
    rng = np.random.default_rng(12)
    cases = _cases(3, rng)
    with ServePipeline(depth=1, window_ms=0.0, batch_sizes=(1,),
                       retries=1, backoff_ms=0.0,
                       breaker_threshold=1, breaker_cooldown_ms=1e6,
                       faults=FaultPlan.parse("nan@c1x*")) as pipe:
        handles = [pipe.submit(c) for c in cases]
        pipe.drain()
    m = pipe.metrics()["resilience"]
    assert [q["case"] for q in m["quarantined"]] == [1]
    assert m["breaker"]["state"] == "closed"
    assert m["breaker"]["transitions"] == []
    assert m["fallback_chunks"] == 0
    assert handles[0].result is not None and handles[2].result is not None


def test_corrupt_half_open_probe_clears_and_recloses_the_breaker():
    # review catch: a half-open probe whose fetch comes back corrupt
    # must CLEAR the probe and re-close the breaker (the device path
    # executed and delivered a buffer — data-shaped corruption attests
    # device health); leaving probe_inflight set would wedge the breaker
    # half-open and route all traffic to the fallback forever
    clock = FakeClock()
    rng = np.random.default_rng(13)
    cases = _cases(4, rng)
    with ServePipeline(depth=1, window_ms=0.0, batch_sizes=(1,),
                       clock=clock, retries=1, backoff_ms=0.0,
                       breaker_threshold=1, breaker_cooldown_ms=50.0,
                       faults=FaultPlan.parse(
                           "raise@0,nan@c2x*")) as pipe:
        handles = [pipe.submit(c) for c in cases[:2]]
        pipe.drain()  # case0: raise -> open; retry + case1 via fallback
        assert pipe.metrics()["resilience"]["breaker"]["state"] == "open"
        clock.advance(0.1)  # cooldown elapses
        handles.append(pipe.submit(cases[2]))  # the probe — corrupt!
        handles.append(pipe.submit(cases[3]))
        pipe.drain()
    m = pipe.metrics()["resilience"]
    moves = [(t["from"], t["to"]) for t in m["breaker"]["transitions"]]
    assert moves == [("closed", "open"), ("open", "half-open"),
                     ("half-open", "closed")]
    assert [q["case"] for q in m["quarantined"]] == [2]
    for i in (0, 1, 3):
        assert handles[i].result is not None, i


def test_nan_policy_serve_keeps_diverged_results():
    # nan_policy="serve" restores PR 3's contract: a non-finite fetched
    # buffer is a legitimate served result, not a fault
    rng = np.random.default_rng(4)
    with ServePipeline(depth=1, window_ms=0.0, batch_sizes=(1,),
                       nan_policy="serve",
                       faults=FaultPlan.parse("nan@0")) as pipe:
        out = pipe.submit(_cases(1, rng)[0]).wait()
    assert not np.all(np.isfinite(out))
    m = pipe.metrics()["resilience"]
    assert m["faults"] == {} and m["retries"] == 0


# -- bisection quarantine ---------------------------------------------------
def test_bisection_isolates_poison_case_mates_served_bit_identical():
    # one 8-case chunk with a persistent NaN on case 5: the chunk is
    # bisected 8 -> 4 -> 2 -> 1 (3 bisections), exactly case 5
    # quarantines, and all 7 mates match the offline engine bit for bit
    # (re-padded halves duplicate their last case, same as offline pads)
    rng = np.random.default_rng(5)
    cases = _cases(8, rng)
    offline = EnsembleEngine(batch_sizes=(8,)).run(cases)
    engine = EnsembleEngine(batch_sizes=(8,))
    # huge window: the SIZE trigger (window_size = top batch size 8)
    # closes the chunk at the 8th submit, so all 8 cases share one chunk
    with ServePipeline(engine=engine, depth=1, window_ms=10_000.0,
                       retries=1, backoff_ms=0.0, fallback=False,
                       faults=FaultPlan.parse("nan@c5x*")) as pipe:
        handles = [pipe.submit(c) for c in cases]
        pipe.drain()
    m = pipe.metrics()["resilience"]
    assert m["bisections"] == 3
    assert [q["case"] for q in m["quarantined"]] == [5]
    assert m["quarantined"][0]["classification"] == "corrupt"
    with pytest.raises(ServeError, match="case 5 quarantined"):
        handles[5].wait()
    for i in range(8):
        if i == 5:
            continue
        assert np.array_equal(handles[i].result, offline[i]), i
    # every failing chunk burned its retry before splitting: 8, 4-half,
    # 2-half, and the isolated case each retried once
    assert m["retries"] == 4
    assert m["faults"] == {"corrupt": 8}
    assert pipe.metrics()["forced_closes"]["bisect"] == 6


# -- circuit breaker --------------------------------------------------------
def test_breaker_unit_lifecycle_with_injected_clock():
    clock = FakeClock()
    br = CircuitBreaker(threshold=2, cooldown_ms=100.0, clock=clock)
    assert br.route() == "device"
    br.record_failure()
    assert br.state == "closed" and br.route() == "device"
    br.record_failure()  # 2 consecutive -> open
    assert br.state == "open" and br.route() == "fallback"
    clock.advance(0.05)
    assert br.route() == "fallback"  # still cooling down
    clock.advance(0.06)
    assert br.route() == "device"  # the half-open probe
    assert br.state == "half-open"
    assert br.route() == "fallback"  # only ONE probe at a time
    br.record_failure()  # probe failed -> open again, timer reset
    assert br.state == "open"
    clock.advance(0.11)
    assert br.route() == "device"
    br.record_success()  # probe succeeded -> closed
    assert br.state == "closed"
    moves = [(t["from"], t["to"]) for t in br.transitions]
    assert moves == [("closed", "open"), ("open", "half-open"),
                     ("half-open", "open"), ("open", "half-open"),
                     ("half-open", "closed")]
    with pytest.raises(ValueError, match="threshold"):
        CircuitBreaker(threshold=0)


def test_breaker_stale_outcomes_never_settle_the_probe():
    # a depth-D pipeline can have chunks dispatched to the device BEFORE
    # the breaker opened that retire while it is half-open: their
    # outcomes (probe=False) must not close the breaker, cancel the
    # probe slot, or re-stamp the open timer — only the probe's own
    # outcome (probe=True) settles half-open
    clock = FakeClock()
    br = CircuitBreaker(threshold=1, cooldown_ms=100.0, clock=clock)
    br.record_failure(probe=False)
    assert br.state == "open"
    clock.advance(0.11)
    assert br.route() == "device" and br.routed_probe  # the probe
    assert br.state == "half-open"
    br.record_success(probe=False)  # stale chunk retires: no transition
    assert br.state == "half-open" and br.probe_inflight
    br.record_failure(probe=False)  # stale failure: probe slot intact
    assert br.state == "half-open" and br.probe_inflight
    assert br.route() == "fallback" and not br.routed_probe
    br.record_success(probe=True)  # the probe's own outcome closes it
    assert br.state == "closed" and not br.probe_inflight
    moves = [(t["from"], t["to"]) for t in br.transitions]
    assert moves == [("closed", "open"), ("open", "half-open"),
                     ("half-open", "closed")]


def test_breaker_transition_trail_bounded_count_exact():
    # a breaker flapping against a persistently dead device accumulates
    # transitions forever; the retained trail is windowed at
    # TRANSITION_CAP while transition_count stays lifetime-exact
    from nonlocalheatequation_tpu.serve.resilience import TRANSITION_CAP

    clock = FakeClock()
    br = CircuitBreaker(threshold=1, cooldown_ms=1.0, clock=clock)
    br.record_failure()  # closed -> open
    flaps = TRANSITION_CAP  # each flap: open -> half-open -> open
    for _ in range(flaps):
        clock.advance(0.002)
        assert br.route() == "device"  # half-open probe
        br.record_failure()  # probe fails -> open again
    assert br.transition_count == 1 + 2 * flaps
    assert len(br.transitions) == TRANSITION_CAP
    assert br.transitions[-1]["to"] == "open"


def test_failed_pipeline_ctor_does_not_leak_the_donation_pin(monkeypatch):
    # ServePipeline pins the process-wide donation depth; a ctor that
    # refuses (malformed ambient plan, bad breaker knobs) must refuse
    # BEFORE pinning — close() never runs on a failed __init__, so a
    # pin taken first would leak to every later solve in the process
    from nonlocalheatequation_tpu.utils import donation

    monkeypatch.setenv("NLHEAT_FAULT_PLAN", "raise@")  # malformed
    with pytest.raises(ValueError, match="fault-plan"):
        ServePipeline(depth=3, batch_sizes=(1,))
    assert donation._pipeline_depth == 1
    monkeypatch.delenv("NLHEAT_FAULT_PLAN")
    with pytest.raises(ValueError, match="threshold"):
        ServePipeline(depth=3, batch_sizes=(1,), breaker_threshold=0)
    assert donation._pipeline_depth == 1


def test_breaker_opens_routes_fallback_probes_and_recloses():
    # pipeline-level lifecycle: two consecutive device failures (one
    # chunk's attempt + retry) open the K=2 breaker; the retry and the
    # next chunks serve via the CPU fallback; after the cooldown the
    # half-open probe re-closes it — results all bit-identical (the CPU
    # suite's fallback sibling builds the same conv programs)
    clock = FakeClock()
    rng = np.random.default_rng(6)
    cases = _cases(4, rng)
    offline = EnsembleEngine(batch_sizes=(1,)).run(cases)
    engine = EnsembleEngine(batch_sizes=(1,))
    with ServePipeline(engine=engine, depth=1, window_ms=0.0, clock=clock,
                       retries=2, backoff_ms=0.0,
                       breaker_threshold=2, breaker_cooldown_ms=1000.0,
                       faults=FaultPlan.parse("raise@0,raise@1")) as pipe:
        handles = [pipe.submit(c) for c in cases[:3]]
        pipe.drain()  # case0: fail, fail (-> open), fallback-served
        m = pipe.metrics()["resilience"]
        assert m["breaker"]["state"] == "open"
        assert m["fallback_chunks"] >= 2  # case0's 3rd attempt + cases 1-2
        clock.advance(1.1)  # past the cooldown
        handles.append(pipe.submit(cases[3]))  # the half-open probe
        pipe.drain()
    m = pipe.metrics()["resilience"]
    assert m["breaker"]["state"] == "closed"
    moves = [(t["from"], t["to"]) for t in m["breaker"]["transitions"]]
    assert moves == [("closed", "open"), ("open", "half-open"),
                     ("half-open", "closed")]
    for h, want in zip(handles, offline, strict=True):
        assert np.array_equal(h.result, want)


# -- the acceptance chaos run ----------------------------------------------
def test_chaos_acceptance_mid_stream_faults_breaker_cycle_and_quarantine():
    """The ISSUE 4 acceptance scenario: an injected mid-stream plan —
    raise at dispatch 1, stall at dispatch 3, NaN at dispatch 5, plus a
    persistent NaN following case 6 — against a supervised pipelined
    (D=3) schedule with a K=1 breaker.  Every non-poison request must
    come back bit-identical to an uninjected offline run, exactly case 6
    must raise ServeError, and the breaker must be OBSERVED (from
    metrics) to open, probe half-open, and re-close."""
    clock = FakeClock()
    rng = np.random.default_rng(7)
    cases = _cases(9, rng)
    offline = EnsembleEngine(batch_sizes=(1,)).run(cases)
    engine = EnsembleEngine(batch_sizes=(1,))
    with ServePipeline(engine=engine, depth=3, window_ms=0.0, clock=clock,
                       retries=1, backoff_ms=0.0, fetch_deadline_ms=100.0,
                       breaker_threshold=1, breaker_cooldown_ms=50.0,
                       sleep=lambda s: None,
                       faults=FaultPlan.parse(
                           "raise@1,stall@3,nan@5,nan@c6x*")) as pipe:
        handles = [pipe.submit(c) for c in cases[:8]]
        pipe.drain()
        m = pipe.metrics()["resilience"]
        assert m["breaker"]["state"] == "open"  # opened at the raise
        clock.advance(0.1)  # cooldown elapses
        handles.append(pipe.submit(cases[8]))  # the half-open probe
        pipe.drain()
    m = pipe.metrics()
    res = m["resilience"]
    # every fault kind fired and was classified
    assert res["faults"]["error"] >= 1
    assert res["faults"]["hang"] >= 1
    assert res["faults"]["corrupt"] >= 2  # the transient + the poison's
    # exactly the poison case quarantined, with the right classification
    assert [q["case"] for q in res["quarantined"]] == [6]
    assert res["quarantined"][0]["classification"] == "corrupt"
    with pytest.raises(ServeError) as ei:
        handles[6].wait()
    assert ei.value.classification == "corrupt" and ei.value.case_seq == 6
    # the breaker cycled: open while faults flowed, fallback served the
    # open window, half-open probe re-closed it
    moves = [(t["from"], t["to"])
             for t in res["breaker"]["transitions"]]
    assert moves == [("closed", "open"), ("open", "half-open"),
                     ("half-open", "closed")]
    assert res["fallback_chunks"] >= 1
    # every non-poison request is bit-identical to the uninjected offline
    # engine — device-served, retried, and fallback-served alike
    for i in range(9):
        if i == 6:
            continue
        assert np.array_equal(handles[i].result, offline[i]), i
    # the telemetry is in the one-call dump the CLIs print
    assert "resilience" in m and "breaker" in m["resilience"]


def test_happy_path_supervision_reports_all_zero_telemetry():
    rng = np.random.default_rng(8)
    cases = _cases(6, rng)
    offline = EnsembleEngine().run(cases)
    with ServePipeline(depth=2, window_ms=0.0) as pipe:
        served = pipe.serve_cases(cases)
    res = pipe.metrics()["resilience"]
    assert res["retries"] == 0 and res["faults"] == {}
    assert res["bisections"] == 0 and res["fallback_chunks"] == 0
    assert res["quarantined"] == [] and res["backoff_ms_total"] == 0.0
    assert res["breaker"]["state"] == "closed"
    assert res["breaker"]["transitions"] == []
    for got, want in zip(served, offline, strict=True):
        assert np.array_equal(got, want)
