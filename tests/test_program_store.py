"""AOT program store (serve/program_store.py) — ISSUE 9.

Contracts pinned here, all on the f64 8-virtual-device CPU suite
(tests/conftest.py):

* a warm boot LOADS a stored executable with zero retrace/recompile
  (spy counters on the engine's builder AND the store's compiler), and
  the served results are bit-identical to a cold compile;
* ``NLHEAT_PROGRAM_STORE=0``/unset restores pre-store behavior
  bit-identically (and, for the solo maker, object-identically: the
  exact donated-jit wrapper the maker returned before the store
  existed);
* every refusal is LOUD and typed, and always falls back to a fresh
  compile, never to wrong results: version-fingerprint mismatch,
  topology mismatch, truncated/corrupt entries (the checkpoint CRC
  discipline), foreign files, deserialization failure;
* concurrent writers (two processes, same key) leave a loadable store
  (atomic_file: unique tmp + atomic replace);
* the engine's in-memory program cache is a bounded LRU whose eviction
  never changes served results.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from nonlocalheatequation_tpu.serve import program_store as ps
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cases(n=3, shape=(24, 24), nt=3, eps=2, seed=0):
    rng = np.random.default_rng(seed)
    return [
        EnsembleCase(shape=shape, nt=nt, eps=eps, k=1.0, dt=1e-5,
                     dh=1.0 / shape[0], test=False,
                     u0=rng.normal(size=shape))
        for _ in range(n)
    ]


def _entries(d):
    return sorted(p for p in os.listdir(d) if p.endswith(".aotprog"))


# -- hit path: zero retrace/recompile, bit-identical ------------------------


def test_warm_boot_zero_retrace_bit_identical(tmp_path, monkeypatch):
    cases = _cases()
    base = EnsembleEngine(method="conv").run(cases)  # storeless oracle

    builds = {"n": 0}
    real_build = EnsembleEngine._build_program

    def spy_build(self, *a, **kw):
        builds["n"] += 1
        return real_build(self, *a, **kw)

    compiles = {"n": 0}
    real_compile = ps.ProgramStore._compile

    def spy_compile(self, *a, **kw):
        compiles["n"] += 1
        return real_compile(self, *a, **kw)

    monkeypatch.setattr(EnsembleEngine, "_build_program", spy_build)
    monkeypatch.setattr(ps.ProgramStore, "_compile", spy_compile)

    d = str(tmp_path)
    cold_eng = EnsembleEngine(method="conv", program_store=d)
    cold = cold_eng.run(cases)
    assert builds["n"] == 1 and compiles["n"] == 1
    assert cold_eng.program_store.stats()["misses"] == 1
    assert cold_eng.program_store.stats()["saves"] == 1
    assert _entries(d)

    warm_eng = EnsembleEngine(method="conv", program_store=d)
    warm = warm_eng.run(cases)
    # the warm boot never traced and never compiled: the stored
    # executable is the program
    assert builds["n"] == 1 and compiles["n"] == 1
    assert warm_eng.program_store.stats() == {
        "hits": 1, "misses": 0, "saves": 0, "gc_evictions": 0,
        "refusals": {}}
    for a, b, c in zip(base, cold, warm, strict=True):
        assert np.array_equal(a, b) and np.array_equal(a, c)
    # honesty: a loaded program's strategy label says where it came from,
    # and the counters split built (traced+compiled HERE) from loaded —
    # a recompile watchdog reading programs-built must see zero on a
    # fully warm boot
    assert set(warm_eng.report.strategies.values()) == {"stored"}
    assert cold_eng.report.programs_built == 1
    assert cold_eng.report.programs_loaded == 0
    assert warm_eng.report.programs_built == 0
    assert warm_eng.report.programs_loaded == 1


def test_store_off_is_todays_behavior_bit_identical(tmp_path, monkeypatch):
    cases = _cases()
    base = EnsembleEngine(method="conv").run(cases)
    # explicit 0 disables even with a dir-shaped value around
    monkeypatch.setenv("NLHEAT_PROGRAM_STORE", "0")
    off = EnsembleEngine(method="conv")
    got = off.run(cases)
    assert off.program_store is None and off._store_resolved
    for a, b in zip(base, got, strict=True):
        assert np.array_equal(a, b)
    # the solo maker returns the EXACT pre-store object when off: the
    # donated-jit wrapper, not a store wrapper (today's path, verbatim)
    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        NonlocalOp2D,
        make_multi_step_fn_base,
    )

    monkeypatch.delenv("NLHEAT_PROGRAM_STORE")
    op = NonlocalOp2D(2, k=1.0, dt=1e-5, dh=1.0 / 24, method="conv")
    fn = make_multi_step_fn_base(op, 3)
    assert fn.__qualname__.startswith("donated_jit")


def test_solo_path_store_hit_bit_identical(tmp_path, monkeypatch):
    import jax.numpy as jnp

    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        NonlocalOp2D,
        make_multi_step_fn_base,
    )

    op = NonlocalOp2D(2, k=1.0, dt=1e-5, dh=1.0 / 24, method="conv")
    u0 = np.random.default_rng(3).normal(size=(24, 24))
    ref = np.asarray(make_multi_step_fn_base(op, 3)(jnp.asarray(u0), 0))

    monkeypatch.setenv("NLHEAT_PROGRAM_STORE", str(tmp_path))
    cold = np.asarray(make_multi_step_fn_base(op, 3)(jnp.asarray(u0), 0))
    assert _entries(str(tmp_path))
    warm = np.asarray(make_multi_step_fn_base(op, 3)(jnp.asarray(u0), 0))
    assert np.array_equal(ref, cold) and np.array_equal(ref, warm)
    # non-zero start steps reuse the same executable (t0 is an argument)
    shifted = np.asarray(make_multi_step_fn_base(op, 3)(jnp.asarray(u0), 5))
    assert shifted.shape == ref.shape


# -- refusals: loud, typed, always recovered --------------------------------


def _store_one(tmp_path):
    """One populated store dir + the oracle results + the case set."""
    cases = _cases()
    eng = EnsembleEngine(method="conv", program_store=str(tmp_path))
    out = eng.run(cases)
    (entry,) = _entries(str(tmp_path))
    return cases, out, os.path.join(str(tmp_path), entry)


def _rerun(tmp_path, cases):
    eng = EnsembleEngine(method="conv", program_store=str(tmp_path))
    return eng.run(cases), eng.program_store.stats()


def test_fingerprint_mismatch_refuses_and_recompiles(
        tmp_path, monkeypatch, capsys):
    from nonlocalheatequation_tpu.utils import compat

    cases, out, _entry = _store_one(tmp_path)
    real_fp = compat.aot_fingerprint()

    def other_build():
        fp = dict(real_fp)
        fp["jaxlib"] = "9.9.9"
        return fp

    monkeypatch.setattr(ps.compat, "aot_fingerprint", other_build)
    got, stats = _rerun(tmp_path, cases)
    assert stats["hits"] == 0
    assert stats["refusals"] == {ps.REFUSE_FINGERPRINT: 1}
    for a, b in zip(out, got, strict=True):
        assert np.array_equal(a, b)  # fresh compile, same results
    err = capsys.readouterr().err
    assert "fingerprint-mismatch" in err and "falling back" in err


def test_topology_mismatch_refuses_and_recompiles(
        tmp_path, monkeypatch, capsys):
    cases, out, _entry = _store_one(tmp_path)
    real_topo = ps.topology_fingerprint()

    def other_topo(backend=None):
        t = dict(real_topo)
        t["devices"] = 1024
        return t

    monkeypatch.setattr(ps, "topology_fingerprint", other_topo)
    got, stats = _rerun(tmp_path, cases)
    assert stats["hits"] == 0
    assert stats["refusals"] == {ps.REFUSE_TOPOLOGY: 1}
    for a, b in zip(out, got, strict=True):
        assert np.array_equal(a, b)
    assert "topology-mismatch" in capsys.readouterr().err


@pytest.mark.parametrize("mutate", ["truncate", "flip", "foreign"])
def test_corrupt_entry_refuses_and_recompiles(
        tmp_path, mutate, capsys):
    cases, out, entry = _store_one(tmp_path)
    raw = open(entry, "rb").read()
    if mutate == "truncate":
        open(entry, "wb").write(raw[: len(raw) // 2])
    elif mutate == "flip":
        body = bytearray(raw)
        body[-10] ^= 0xFF  # payload bit-rot: the CRC must catch it
        open(entry, "wb").write(bytes(body))
    else:
        open(entry, "wb").write(b"not a program store entry")
    got, stats = _rerun(tmp_path, cases)
    assert stats["hits"] == 0
    assert stats["refusals"] == {ps.REFUSE_CORRUPT: 1}
    for a, b in zip(out, got, strict=True):
        assert np.array_equal(a, b)
    assert "corrupt" in capsys.readouterr().err
    # the refused entry was re-persisted by the fresh compile and loads
    # cleanly on the next boot
    got2, stats2 = _rerun(tmp_path, cases)
    assert stats2["hits"] == 1 and stats2["refusals"] == {}
    for a, b in zip(out, got2, strict=True):
        assert np.array_equal(a, b)


def test_unsupported_serialization_degrades_loudly(
        tmp_path, monkeypatch, capsys):
    # a build with no serialize_executable at all: the store refuses
    # ONCE (loudly), every program runs the plain fresh-compile path,
    # results are unchanged
    monkeypatch.setattr(ps.compat, "aot_serialize_supported", lambda: False)
    cases = _cases()
    base = EnsembleEngine(method="conv").run(cases)
    eng = EnsembleEngine(method="conv", program_store=str(tmp_path))
    got = eng.run(cases)
    for a, b in zip(base, got, strict=True):
        assert np.array_equal(a, b)
    assert eng.program_store.stats()["refusals"] == {
        ps.REFUSE_UNSUPPORTED: 1}
    assert not _entries(str(tmp_path))
    assert "unsupported" in capsys.readouterr().err


# -- concurrency: two-process writer race -----------------------------------

_RACE_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
import numpy as np
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase, EnsembleEngine)
rng = np.random.default_rng(0)
cases = [EnsembleCase(shape=(24, 24), nt=3, eps=2, k=1.0, dt=1e-5,
                      dh=1.0 / 24, test=False,
                      u0=rng.normal(size=(24, 24))) for _ in range(3)]
eng = EnsembleEngine(method="conv", program_store=sys.argv[1])
out = eng.run(cases)
np.save(sys.argv[2], np.stack(out))
st = eng.program_store.stats()
print("STATS", st["hits"], st["misses"], st["saves"])
"""


def test_two_process_writer_race_leaves_loadable_store(tmp_path):
    # both processes compute the SAME key concurrently; atomic_file's
    # host+pid-unique tmp + os.replace means both may save, the last
    # replace wins, and no reader can ever observe a torn entry
    d = str(tmp_path / "store")
    env = dict(os.environ)
    env.pop("NLHEAT_PROGRAM_STORE", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _RACE_CHILD, d,
             str(tmp_path / f"out{i}.npy")],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        for i in range(2)
    ]
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err[-800:]
        assert "STATS" in out
    a = np.load(tmp_path / "out0.npy")
    b = np.load(tmp_path / "out1.npy")
    assert np.array_equal(a, b)
    assert len(_entries(d)) == 1  # one key, one complete winner
    # a third boot must warm-load the raced entry and agree bitwise
    cases, stats = _cases(), None
    eng = EnsembleEngine(method="conv", program_store=d)
    got = eng.run(cases)
    stats = eng.program_store.stats()
    assert stats["hits"] == 1 and stats["refusals"] == {}
    assert np.array_equal(np.stack(got), a)


# -- LRU program cache ------------------------------------------------------


def test_lru_eviction_never_changes_results():
    # two buckets, cap 1: every build evicts the other bucket's program;
    # results must equal the uncapped engine's bit for bit
    cases = _cases(3, shape=(24, 24)) + _cases(2, shape=(16, 16), seed=7)
    base = EnsembleEngine(method="conv").run(cases)
    capped = EnsembleEngine(method="conv", program_cache_cap=1)
    got = capped.run(cases)
    for a, b in zip(base, got, strict=True):
        assert np.array_equal(a, b)
    assert capped.report.programs_resident == 1
    assert capped.report.programs_evicted >= 1
    # rerunning re-builds evicted programs transparently, same results
    got2 = capped.run(cases)
    for a, b in zip(base, got2, strict=True):
        assert np.array_equal(a, b)


def test_lru_counters_in_registry_and_cap_validation():
    eng = EnsembleEngine(method="conv", program_cache_cap=2)
    eng.run(_cases(2))
    r = eng.report.registry
    assert r.get("/store/resident-programs").value == 1
    assert r.get("/store/evictions").value == 0
    # the repo-wide 0-knob convention: 0 = cap OFF (unbounded, the
    # pre-LRU behavior), only negatives are malformed
    unbounded = EnsembleEngine(method="conv", program_cache_cap=0)
    assert unbounded.program_cache_cap == float("inf")
    unbounded.run(_cases(2) + _cases(2, shape=(16, 16), seed=5))
    assert unbounded.report.programs_evicted == 0
    with pytest.raises(ValueError, match="program_cache_cap"):
        EnsembleEngine(method="conv", program_cache_cap=-1)


def test_lru_cap_env_knob(monkeypatch):
    monkeypatch.setenv("NLHEAT_PROGRAM_CACHE_CAP", "1")
    eng = EnsembleEngine(method="conv")
    assert eng.program_cache_cap == 1


# -- serving pipeline + fallback share one namespace ------------------------


def test_pipeline_serves_from_store_and_reports_metrics(tmp_path):
    from nonlocalheatequation_tpu.serve.server import ServePipeline

    cases = _cases(5)
    offline = EnsembleEngine(method="conv").run(cases)
    d = str(tmp_path)
    # boot 1 populates; boot 2 must serve every chunk from the store
    for boot in range(2):
        pipe = ServePipeline(method="conv", depth=2, window_ms=0.0,
                             program_store=d)
        got = pipe.serve_cases(cases)
        m = pipe.metrics()
        pipe.close()
        for a, b in zip(offline, got, strict=True):
            assert np.array_equal(a, b)
        assert set(m["store"]) == {
            "hits", "misses", "saves", "refusals", "load_ms",
            "serialize_ms", "resident_programs", "evictions"}
        if boot == 1:
            assert m["store"]["hits"] >= 1 and m["store"]["misses"] == 0
    # the registry expositions carry the /store metrics too
    assert "nlheat_store_hits" in pipe.report.registry.prometheus()


def test_cpu_fallback_sibling_keys_by_backend(tmp_path):
    # the fallback sibling shares the store NAMESPACE (one store object)
    # but its digests pin backend="cpu" — on real hardware a TPU-compiled
    # entry and its CPU-fallback twin can never collide.  On this CPU
    # suite both engines resolve to the same backend, so the sibling
    # legitimately HITS the device engine's entry (same program, same
    # backend); the backend separation itself is pinned on the digest.
    from nonlocalheatequation_tpu.serve.resilience import CpuFallback

    d = str(tmp_path)
    cases = _cases(2)
    eng = EnsembleEngine(method="conv", program_store=d,
                         batch_sizes=(1, 2))
    out = eng.run(cases)
    fb = CpuFallback(eng)
    key = cases[0].bucket_key()
    padded = eng.pad_chunk(list(cases))
    fb_out = fb.run_chunk(key, padded)
    sib = fb._engines["conv"]
    assert sib.store_backend == "cpu"
    assert sib.program_store is eng.program_store  # one shared namespace
    assert eng.program_store.stats()["refusals"] == {}
    for a, b in zip(out, fb_out, strict=True):
        assert np.array_equal(a, np.asarray(b))
    # the backend is load-bearing in the key: same program key, avals,
    # and donation, different backend -> different digest
    assert ps._digest("k", "a", False, "tpu") != \
        ps._digest("k", "a", False, "cpu")


def test_engine_settings_outside_prog_key_separate_store_entries(tmp_path):
    # the in-memory prog_key omits method/precision/ksteps (they are
    # fixed per engine), but the shared store must key on them: two
    # engines differing ONLY there can never load each other's
    # executables (review finding, round 11)
    d = str(tmp_path)
    cases = _cases()
    a = EnsembleEngine(method="conv", program_store=d)
    out_a = a.run(cases)
    assert a.program_store.stats()["misses"] == 1
    for other in (EnsembleEngine(method="shift", program_store=d),
                  EnsembleEngine(method="conv", precision="bf16",
                                 program_store=d)):
        got = other.run(cases)
        st = other.program_store.stats()
        assert st["hits"] == 0, f"{other.method}/{other.precision} hit!"
        assert len(got) == len(out_a)
    # same settings -> hit, as before
    b = EnsembleEngine(method="conv", program_store=d)
    out_b = b.run(cases)
    assert b.program_store.stats()["hits"] == 1
    for x, y in zip(out_a, out_b, strict=True):
        assert np.array_equal(x, y)


def test_trace_env_knobs_join_the_digest(tmp_path, monkeypatch):
    # a tile-size A/B (NLHEAT_TM) builds a DIFFERENT kernel for the same
    # logical key: the digest must separate them so a warm boot can never
    # serve the other arm's executable (review finding, round 11)
    cases, _out, _entry = _store_one(tmp_path)
    plain = ps._digest("k", "a", False, "cpu")
    monkeypatch.setenv("NLHEAT_TM", "128")
    assert ps._digest("k", "a", False, "cpu") != plain
    _got, stats = _rerun(tmp_path, cases)
    assert stats["hits"] == 0 and stats["misses"] == 1


def test_solo_wrapper_non_int_t0_falls_back(tmp_path, monkeypatch):
    # a typed-array t0 (e.g. an autotune probe's jnp scalar) mismatches
    # the weak-int aval store programs are lowered for: the wrapper must
    # route such calls through the jit path, never raise (review finding)
    import jax.numpy as jnp

    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        NonlocalOp2D,
        make_multi_step_fn_base,
    )

    op = NonlocalOp2D(2, k=1.0, dt=1e-5, dh=1.0 / 24, method="conv")
    u0 = np.random.default_rng(3).normal(size=(24, 24))
    monkeypatch.setenv("NLHEAT_PROGRAM_STORE", str(tmp_path))
    fn = make_multi_step_fn_base(op, 3)
    ref = np.asarray(fn(jnp.asarray(u0), 0))  # int t0: the store path
    typed = np.asarray(fn(jnp.asarray(u0), jnp.int32(0)))
    assert np.array_equal(ref, typed)


def test_solo_store_counters_reach_process_registry(tmp_path, monkeypatch):
    import jax.numpy as jnp

    from nonlocalheatequation_tpu.obs.metrics import REGISTRY
    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        NonlocalOp2D,
        make_multi_step_fn_base,
    )

    op = NonlocalOp2D(2, k=1.0, dt=1e-5, dh=1.0 / 24, method="conv")
    u0 = np.random.default_rng(3).normal(size=(24, 24))
    monkeypatch.setenv("NLHEAT_PROGRAM_STORE", str(tmp_path))
    hits0 = getattr(REGISTRY.get("/store/hits"), "value", 0)
    make_multi_step_fn_base(op, 4)(jnp.asarray(u0), 0)
    make_multi_step_fn_base(op, 4)(jnp.asarray(u0), 0)  # fresh maker: hit
    assert REGISTRY.get("/store/hits").value == hits0 + 1


def test_donation_flip_rematerializes_store_backed_program(
        tmp_path, monkeypatch):
    # store-materialized programs are donation-FIXED binaries, unlike
    # the lazy donated_jit wrappers the plain path caches — the donate
    # decision must join the in-memory cache key so a donation flip
    # (or a depth>1 pipeline pinning donation off) never dispatches a
    # stale donating executable (review finding, round 11)
    cases = _cases()
    base = EnsembleEngine(method="conv").run(cases)
    monkeypatch.setenv("NLHEAT_DONATE", "1")
    eng = EnsembleEngine(method="conv", program_store=str(tmp_path))
    got1 = eng.run(cases)
    assert len(eng._programs) == 1
    monkeypatch.setenv("NLHEAT_DONATE", "0")
    got2 = eng.run(cases)
    # the flip re-materialized under a new (prog_key, donate) entry
    assert len(eng._programs) == 2
    for a, b, c in zip(base, got1, got2, strict=True):
        assert np.array_equal(a, b) and np.array_equal(a, c)


def test_pipeline_adopting_prewarmed_engine_keeps_store_metrics(tmp_path):
    # an engine that ran BEFORE pipeline construction bound its store
    # metrics to the engine report's registry; the pipeline replaces the
    # report, and the store must re-bind — pipe.metrics()["store"] has
    # to see the serve-time hits, not zeros (review finding, round 11)
    from nonlocalheatequation_tpu.serve.server import ServePipeline

    cases = _cases()
    eng = EnsembleEngine(method="conv", program_store=str(tmp_path))
    eng.run(cases)  # pre-warm: resolves the store on eng's own report
    serve_cases = _cases(2, shape=(16, 16), seed=9)  # a fresh bucket
    offline = EnsembleEngine(method="conv").run(serve_cases)
    pipe = ServePipeline(engine=eng, depth=2, window_ms=0.0)
    got = pipe.serve_cases(serve_cases)
    m = pipe.metrics()
    pipe.close()
    for a, b in zip(offline, got, strict=True):
        assert np.array_equal(a, b)
    # the serve-time store activity (fresh bucket -> miss + save) is
    # visible through the PIPELINE's registry, not lost on the old one
    assert m["store"]["misses"] >= 1 and m["store"]["saves"] >= 1


# -- store internals --------------------------------------------------------


def test_store_gc_evicts_lru_within_cap(tmp_path):
    # ISSUE 10 satellite (round11 carried-forward): a fleet's shared dir
    # grows without bound with key diversity — the store evicts
    # least-recently-USED entries past cap_bytes, never the entry just
    # written, counting /store/gc-evictions
    import time as _time

    d = tmp_path / "store"
    d.mkdir()
    store = ps.ProgramStore(str(d), cap_bytes=150)
    now = _time.time()
    for i in range(3):
        p = d / f"e{i}.aotprog"
        p.write_bytes(b"x" * 60)
        os.utime(p, (now - 100 + i, now - 100 + i))
    # a load hit refreshes recency: touch e0 so e1 becomes the LRU
    os.utime(d / "e0.aotprog", None)
    kept = d / "kept.aotprog"
    kept.write_bytes(b"x" * 60)
    os.utime(kept, (now - 200, now - 200))  # oldest mtime of all...
    removed = store._gc(keep=str(kept))  # ...but never self-evicted
    assert removed == 2
    assert store.stats()["gc_evictions"] == 2
    left = set(_entries(d))
    assert "kept.aotprog" in left and "e0.aotprog" in left
    assert left == {"kept.aotprog", "e0.aotprog"}
    # two-process-safe delete: a file another GC already removed is a
    # skipped eviction, not an error
    ghost = d / "ghost.aotprog"
    ghost.write_bytes(b"x" * 500)
    real_remove = os.remove

    def racing_remove(path):
        if path.endswith("ghost.aotprog"):
            real_remove(path)  # the "other process" wins first
        real_remove(path)

    import unittest.mock as mock

    with mock.patch("os.remove", racing_remove):
        store._gc()
    assert "ghost.aotprog" not in _entries(d)


def test_store_gc_end_to_end_saves_trigger_eviction(tmp_path, monkeypatch):
    # real saves over a tiny cap: key diversity (distinct nt buckets)
    # writes several entries, the cap keeps the DIR bounded, and a
    # post-eviction engine still serves (fresh compile on the evicted
    # key — eviction can never change results, only re-pay a compile)
    d = tmp_path / "store"
    monkeypatch.setenv("NLHEAT_PROGRAM_STORE_CAP_MB", "0.02")  # ~20 KB
    store = ps.ProgramStore(str(d))
    assert store.cap_bytes == int(0.02 * 1024 * 1024)
    engine = EnsembleEngine(method="conv", batch_sizes=(1,),
                            program_store=store)
    cases = [_cases(1, nt=3 + i, seed=i)[0] for i in range(4)]
    want = EnsembleEngine(method="conv", batch_sizes=(1,)).run(cases)
    got = engine.run(cases)
    assert all(np.array_equal(a, b) for a, b in zip(want, got, strict=True))
    stats = engine.program_store.stats()
    assert stats["saves"] == 4
    sizes = sum(os.path.getsize(os.path.join(d, p)) for p in _entries(d))
    if stats["gc_evictions"]:  # entry size is backend-dependent; when
        # the cap engaged, the dir must have stayed within it
        assert sizes <= store.cap_bytes
        # an evicted key re-serves via fresh compile, bit-identically
        engine2 = EnsembleEngine(method="conv", batch_sizes=(1,),
                                 program_store=ps.ProgramStore(str(d)))
        got2 = engine2.run(cases)
        assert all(np.array_equal(a, b) for a, b in zip(want, got2, strict=True))


def test_store_cap_env_refusals(monkeypatch):
    monkeypatch.setenv("NLHEAT_PROGRAM_STORE_CAP_MB", "0")
    assert ps.store_cap_from_env() is None  # 0 = unbounded (0-knob rule)
    monkeypatch.delenv("NLHEAT_PROGRAM_STORE_CAP_MB")
    assert ps.store_cap_from_env() is None
    monkeypatch.setenv("NLHEAT_PROGRAM_STORE_CAP_MB", "-1")
    with pytest.raises(ValueError, match="CAP_MB must be >= 0"):
        ps.store_cap_from_env()


def test_env_dir_resolution(monkeypatch):
    monkeypatch.delenv("NLHEAT_PROGRAM_STORE", raising=False)
    assert ps.store_dir_from_env() is None
    monkeypatch.setenv("NLHEAT_PROGRAM_STORE", "0")
    assert ps.store_dir_from_env() is None
    monkeypatch.setenv("NLHEAT_PROGRAM_STORE", "1")
    assert ps.store_dir_from_env() == ps.DEFAULT_DIR
    monkeypatch.setenv("NLHEAT_PROGRAM_STORE", "/tmp/somewhere")
    assert ps.store_dir_from_env() == "/tmp/somewhere"


def test_store_spans_are_emitted(tmp_path):
    from nonlocalheatequation_tpu.obs.trace import Tracer, set_tracer

    tracer = Tracer()
    set_tracer(tracer)
    try:
        cases = _cases()
        EnsembleEngine(method="conv", program_store=str(tmp_path)).run(cases)
        EnsembleEngine(method="conv", program_store=str(tmp_path)).run(cases)
    finally:
        set_tracer(None)
    names = {e["name"] for e in tracer.events}
    assert "store.save" in names and "store.load" in names
