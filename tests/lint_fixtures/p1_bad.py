"""A parity-relevant module whose docstring hand-waves at the reference
without a single file:line citation anyone could check."""


def apply(u):
    return u
