"""W2 bad: JAX_PLATFORMS env writes (ignored by the axon plugin)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.update({"JAX_PLATFORMS": "cpu"})
