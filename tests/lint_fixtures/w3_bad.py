"""W3 bad: an explicit-f64 scan with no platform guard anywhere."""
import jax.numpy as jnp
from jax import lax


def run(xs):
    def body(c, x):
        return c + x, c

    init = jnp.zeros((4,), dtype=jnp.float64)
    return lax.scan(body, init, xs)


def count(n):
    return lax.fori_loop(0, n, lambda i, c: c + i,
                         jnp.asarray(0.0, "float64"))
