"""W2 good: platform forced through the config API; unrelated env
writes stay unflagged."""
import os

import jax

jax.config.update("jax_platforms", "cpu")
os.environ["NLHEAT_DONATE"] = "0"  # unrelated knob: not W2's business
platform = os.environ.get("JAX_PLATFORMS")  # a READ is fine
