"""W1 bad: bare device queries outside the wedge-proof wrappers."""
import jax

ndev = len(jax.devices())
count = jax.device_count()
first_cpu = jax.devices("cpu")[0]
