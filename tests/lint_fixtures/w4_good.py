"""W4 good: the scalar-sum fence, plus an annotated synchronization
use (suppressions carry a reason and survive the scan)."""
import time

import jax.numpy as jnp


def time_steps(step, u, n):
    t0 = time.perf_counter()
    for _ in range(n):
        u = step(u)
    fence = float(jnp.sum(u))  # the honest fence over the tunnel
    return time.perf_counter() - t0, fence


def throttle(queue, depth):
    if len(queue) > depth:
        # lint-ok: W4 backpressure on the dispatch queue, not a timing fence
        queue.pop(0).block_until_ready()
