"""L1 good: every mutation under the declared lock, or in a method
annotated as called-with-lock-held."""
import threading


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = {}  # guarded_by: self._lock
        self._done = 0  # guarded_by: self._lock
        self._hits = 0  # unguarded by declaration: single-thread stat

    def submit(self, k, v):
        with self._lock:
            self._pending[k] = v

    def on_reader_thread(self, k):
        with self._lock:
            self._pending.pop(k, None)
            self._done += 1

    def _sweep(self, keys):  # locked: self._lock
        for k in keys:
            del self._pending[k]

    def count(self):
        self._hits += 1  # undeclared attr: L1 has no opinion
        with self._lock:
            return len(self._pending)
