"""L1 bad: a guarded attribute mutated off-lock from a second thread
entry point."""
import threading


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = {}  # guarded_by: self._lock
        self._done = 0  # guarded_by: self._lock

    def submit(self, k, v):
        with self._lock:
            self._pending[k] = v

    def on_reader_thread(self, k):
        self._pending.pop(k, None)  # off-lock mutation: the bug
        self._done += 1  # off-lock augmented assignment: also the bug
