"""A parity-relevant module citing its reference behavior precisely
(the operator loop of src/2d_nonlocal_serial.cpp:213-221)."""


def apply(u):
    return u
