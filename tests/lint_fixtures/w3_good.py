"""W3 good: backend-derived dtypes, or an explicit f64 scan behind a
platform guard."""
import jax
import jax.numpy as jnp
from jax import lax


def run(xs, dtype):
    # dtype inherited from the caller/backend: out of W3's scope
    def body(c, x):
        return c + x, c

    return lax.scan(body, jnp.zeros((4,), dtype=dtype), xs)


def run_f64_guarded(xs):
    if jax.default_backend() == "tpu":
        raise RuntimeError("f64 scan refused on the TPU (wedge trigger)")
    init = jnp.zeros((4,), dtype=jnp.float64)
    return lax.scan(lambda c, x: (c + x, c), init, xs)
