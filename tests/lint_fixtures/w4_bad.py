"""W4 bad: block_until_ready as a benchmark fence."""
import time

import jax.numpy as jnp


def time_steps(step, u, n):
    t0 = time.perf_counter()
    for _ in range(n):
        u = step(u)
    u.block_until_ready()
    return time.perf_counter() - t0, jnp.sum(u)
