"""W1 good: device queries via the sanctioned accessor."""
import jax

from nonlocalheatequation_tpu.utils.devices import device_count, device_list

ndev = len(device_list())
count = device_count()
first_cpu = device_list("cpu")[0]
backend = jax.default_backend()  # not a device query; never flagged
