"""Fused halo engine (ops/pallas_halo.py) on the 8-virtual-device mesh.

The contract under test: ``comm='fused'`` — the split/remote-DMA kernel
family with the interior-then-ring compute decomposition — is BITWISE
the ``comm='collective'`` pallas path (same plan, same op order; the
module docstring's sub-rectangle invariance), and both hold the serial
oracle to 1e-12.  On CPU the fused path runs the split kernel in the
Pallas interpreter under the ppermute transport, so tier-1 exercises
the fused kernel body without a TPU; the RDMA transport itself is
on-device evidence (dryrun_multichip / the multichip bench rung).

Also here: the exchange-plan geometry (the reference's 8 neighbor
rectangles, hop-capped multi-hop widths), the parallel/halo.py
byte-cap regression (exchanged ppermute bytes pinned via the jaxpr),
the comm engine-key plumbing, and the /halo/* obs wiring.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.models.solver3d import Solver3D
from nonlocalheatequation_tpu.ops import pallas_halo as ph
from nonlocalheatequation_tpu.parallel.distributed2d import Solver2DDistributed
from nonlocalheatequation_tpu.parallel.distributed3d import Solver3DDistributed
from nonlocalheatequation_tpu.parallel.halo import halo_pad_2d, hop_widths
from nonlocalheatequation_tpu.parallel.mesh import make_mesh, make_mesh_3d
from nonlocalheatequation_tpu.utils.compat import shard_map


def _pair_2d(mesh, npx, npy, nx, ny, nt, eps, **kw):
    """(fused, collective) 2D solvers on one shared mesh, pallas both."""
    base = dict(nt=nt, eps=eps, k=kw.pop("k", 1.0), dt=kw.pop("dt", 1e-4),
                dh=kw.pop("dh", 0.02), mesh=mesh, method="pallas", **kw)
    return (Solver2DDistributed(nx, ny, npx, npy, comm="fused", **base),
            Solver2DDistributed(nx, ny, npx, npy, comm="collective", **base))


# -- bit-identity: fused vs collective vs serial oracle ---------------------


@pytest.mark.parametrize("mx,my", [(4, 2), (2, 4), (8, 1)])
@pytest.mark.parametrize("eps", [1, 2])
def test_fused_bitwise_vs_collective_2d(mx, my, eps):
    # non-square meshes included: the band geometry is axis-asymmetric
    mesh = make_mesh(mx, my)
    f, c = _pair_2d(mesh, mx, my, 8, 8, nt=3, eps=eps)
    o = Solver2D(8 * mx, 8 * my, 3, eps=eps, k=1.0, dt=1e-4, dh=0.02,
                 backend="oracle")
    for s in (f, c, o):
        s.test_init()
    uf, uc, uo = f.do_work(), c.do_work(), o.do_work()
    assert np.array_equal(uf, uc), (
        f"fused deviates from the collective oracle by "
        f"{np.abs(uf - uc).max():.3e}")
    assert np.abs(uf - uo).max() < 1e-12
    # the manufactured-solution contract holds on the fused path
    assert f.error_l2 / (8 * mx * 8 * my) <= 1e-6


@pytest.mark.parametrize("eps", [9, 17])
def test_fused_multihop_2d(eps):
    # shard edge 8 < eps: hops ceil(eps/8) — the fused plan DMAs the
    # capped band straight to the device m hops away; still bitwise
    mesh = make_mesh(4, 2)
    f, c = _pair_2d(mesh, 4, 2, 8, 8, nt=2, eps=eps)
    o = Solver2D(32, 16, 2, eps=eps, k=1.0, dt=1e-4, dh=0.02,
                 backend="oracle")
    for s in (f, c, o):
        s.test_init()
    uf, uc, uo = f.do_work(), c.do_work(), o.do_work()
    assert np.array_equal(uf, uc)
    assert np.abs(uf - uo).max() < 1e-12


def test_fused_production_path_2d():
    # non-test (source-free) path: free decay from random state
    rng = np.random.default_rng(0)
    mesh = make_mesh(4, 2)
    f, c = _pair_2d(mesh, 4, 2, 10, 10, nt=4, eps=3)
    u0 = rng.normal(size=(40, 20))
    f.input_init(u0)
    c.input_init(u0)
    assert np.array_equal(f.do_work(), c.do_work())


@pytest.mark.parametrize("eps", [2, 5])
def test_fused_bitwise_vs_collective_3d(eps):
    # 2x2x2 mesh, block edge 4: eps=5 is the multi-hop 3D case
    mesh = make_mesh_3d(2, 2, 2, devices=jax.devices()[:8])
    base = dict(nt=2, eps=eps, k=1.0, dt=1e-4, dh=0.05, mesh=mesh,
                method="pallas")
    f = Solver3DDistributed(8, 8, 8, comm="fused", **base)
    c = Solver3DDistributed(8, 8, 8, comm="collective", **base)
    o = Solver3D(8, 8, 8, 2, eps=eps, k=1.0, dt=1e-4, dh=0.05,
                 backend="oracle")
    for s in (f, c, o):
        s.test_init()
    uf, uc, uo = f.do_work(), c.do_work(), o.do_work()
    assert np.array_equal(uf, uc)
    assert np.abs(uf - uo).max() < 1e-12


def test_fused_bf16_pair_frames():
    # the bf16 tier rides the fused kernels too: operand round-trip in
    # kernel, f32-or-better accumulate — bitwise the collective bf16 path
    mesh = make_mesh(4, 2)
    f, c = _pair_2d(mesh, 4, 2, 8, 8, nt=3, eps=2, precision="bf16",
                    dtype=jnp.float32)
    for s in (f, c):
        s.test_init()
    assert np.array_equal(f.do_work(), c.do_work())


def test_split_kernel_interpret_mode_direct():
    # the fused kernel BODY runs in the Pallas interpreter on CPU — the
    # tier-1 stand-in for the on-device kernel — and is bitwise the
    # oracle neighbor sum on a pre-filled frame
    from nonlocalheatequation_tpu.ops.nonlocal_op import NonlocalOp2D
    from nonlocalheatequation_tpu.ops.pallas_kernel import _window_pad

    assert ph.fused_transport() == "interp"  # CPU suite: interpreter
    rng = np.random.default_rng(1)
    bx, by, eps = 24, 16, 3
    op = NonlocalOp2D(eps, 1.0, 1e-4, 0.02, method="pallas")
    upad = rng.normal(size=(bx + 2 * eps, by + 2 * eps))
    want = np.asarray(op.neighbor_sum_padded(jnp.asarray(upad)))
    frame = jnp.asarray(np.pad(upad, ((0, _window_pad(eps)), (0, 0))))
    got = ph.build_split_nsum_2d(eps, bx, by, "float64")(frame)
    assert np.array_equal(np.asarray(got), want)


# -- the exchange plan: neighbor rectangles, hop caps -----------------------


def test_plan_exchange_eight_neighbors_one_hop():
    # one hop: 8 messages — exactly the reference's 8-neighbor tiles
    plan = ph.plan_exchange((4, 2), (16, 8), 3)
    assert len(plan) == 8
    assert sorted(m.offset for m in plan) == sorted(
        (dx, dy) for dx in (-1, 0, 1) for dy in (-1, 0, 1)
        if (dx, dy) != (0, 0))
    for m in plan:
        # bands are eps wide on their offset axes, full extent on axis 0
        for ax, o in enumerate(m.offset):
            w = m.src[ax][1] - m.src[ax][0]
            assert w == (3 if o else (16, 8)[ax])
            # dst ranges live inside the receiver frame
            lo, hi = m.dst[ax]
            assert 0 <= lo < hi <= (16, 8)[ax] + 6


def test_plan_exchange_multihop_capped_widths():
    # eps=9 on 8-wide blocks: hops (8, 1) — the final hop carries ONE
    # row, not a full block (the round-9 byte-cap fix, shared with the
    # collective ring)
    assert hop_widths(9, 8) == (8, 1)
    plan = ph.plan_exchange((4, 1), (8, 8), 9)
    by_off = {m.offset: m for m in plan}
    assert set(by_off) == {(-2, 0), (-1, 0), (1, 0), (2, 0)}
    assert by_off[(1, 0)].shape == (8, 8)
    assert by_off[(2, 0)].shape == (1, 8)  # capped
    assert by_off[(2, 0)].src[0] == (7, 8)  # the trailing row
    assert by_off[(2, 0)].dst[0] == (0, 1)  # deepest halo row
    # hops never exceed the mesh: 2 shards -> 1 hop only, the rest of
    # the horizon is the zero collar (volumetric BC)
    plan2 = ph.plan_exchange((2, 1), (8, 8), 9)
    assert {m.offset for m in plan2} == {(-1, 0), (1, 0)}


def test_plan_bytes_match_collective_single_hop():
    # at one hop with no sharded-axis asymmetry, direct corner sends
    # carry exactly what the two-phase collective carries in-band
    plan = ph.plan_exchange((2, 4), (16, 8), 3)
    assert ph.plan_bytes(plan, 8) == ph.collective_bytes((2, 4), (16, 8),
                                                         3, 8)


# -- parallel/halo.py byte-cap regression (jaxpr-pinned) --------------------


def _ppermute_bytes(jaxpr) -> int:
    """Total bytes every ppermute eqn of a (nested) jaxpr transfers per
    device — the exchanged-byte meter for the regression pin."""
    import jax.core as core

    total = 0
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "ppermute":
            aval = eqn.invars[0].aval
            total += int(np.prod(aval.shape)) * aval.dtype.itemsize
        for v in eqn.params.values():
            for sub in (v if isinstance(v, (list, tuple)) else (v,)):
                if isinstance(sub, core.ClosedJaxpr):
                    total += _ppermute_bytes(sub.jaxpr)
                elif isinstance(sub, core.Jaxpr):
                    total += _ppermute_bytes(sub)
    return total


@pytest.mark.parametrize("eps,block", [(3, (8, 8)), (9, (8, 8)),
                                       (17, (8, 8)), (5, (16, 8))])
def test_exchanged_bytes_capped(eps, block):
    # the multi-hop ring must transfer min(bs, remaining-depth)-wide
    # bands, not full blocks every hop; collective_bytes is the capped
    # formula and the traced jaxpr must agree with it exactly
    mesh_shape = (4, 2)
    mesh = make_mesh(*mesh_shape)

    def local(u):
        return halo_pad_2d(u, eps, mesh_shape)

    f = shard_map(local, mesh=mesh, in_specs=P("x", "y"),
                  out_specs=P("x", "y"), check_vma=False)
    g = (block[0] * mesh_shape[0], block[1] * mesh_shape[1])
    jaxpr = jax.make_jaxpr(f)(jnp.zeros(g))
    got = _ppermute_bytes(jaxpr.jaxpr)
    want = ph.collective_bytes(mesh_shape, block, eps, 8)
    assert got == want, f"ppermute'd {got} bytes, capped plan says {want}"
    if eps > block[0]:
        # the pre-fix ring re-permuted full-width bands every hop;
        # assert the cap actually bites on the multi-hop configs
        hops_x = -(-eps // block[0])
        uncapped_x = 2 * hops_x * block[0] * block[1] * 8
        capped_x = 2 * sum(hop_widths(eps, block[0])) * block[1] * 8
        assert capped_x < uncapped_x
        assert got < want + (uncapped_x - capped_x)


def test_multihop_values_unchanged_by_cap():
    # the cap moves fewer bytes but the stitched halo is value-identical:
    # distributed multi-hop still matches the serial oracle (the eps=7 /
    # shard-5 case of test_distributed, re-pinned here against the fix)
    o = Solver2D(20, 20, 10, eps=7, k=0.2, dt=5e-4, dh=0.02,
                 backend="oracle")
    d = Solver2DDistributed(20, 20, 1, 1, nt=10, eps=7, k=0.2, dt=5e-4,
                            dh=0.02, mesh=make_mesh(4, 2))
    o.test_init()
    d.test_init()
    assert np.abs(o.do_work() - d.do_work()).max() < 1e-12


# -- refusals and engine-key plumbing ---------------------------------------


def test_fused_refusals():
    mesh = make_mesh(4, 2)
    kw = dict(nt=2, eps=2, k=1.0, dt=1e-4, dh=0.02, mesh=mesh)
    with pytest.raises(ValueError, match="method='pallas'"):
        Solver2DDistributed(8, 8, 4, 2, method="conv", comm="fused", **kw)
    with pytest.raises(ValueError, match="superstep"):
        Solver2DDistributed(8, 8, 4, 2, method="pallas", comm="fused",
                            superstep=2, **kw)
    with pytest.raises(ValueError, match="collective' or 'fused"):
        Solver2DDistributed(8, 8, 4, 2, comm="rdma", **kw)
    # a block too large for the halo-resident VMEM frame is refused with
    # guidance at CONSTRUCTION (the gate is the stack model, not Mosaic)
    assert not ph.fits_fused((8192, 8192), 8, jnp.float32)
    with pytest.raises(ValueError, match="VMEM"):
        ph.require_fused(
            type("Op", (), {"method": "pallas", "uniform": True,
                            "eps": 8, "precision": "f32"})(),
            (8192, 8192), jnp.float32)


def test_ensemble_comm_joins_engine_key():
    from nonlocalheatequation_tpu.serve.ensemble import (
        EnsembleCase,
        EnsembleEngine,
    )

    with pytest.raises(ValueError, match="method='pallas'"):
        EnsembleEngine(method="sat", comm="fused")
    with pytest.raises(ValueError, match="comm"):
        EnsembleEngine(comm="rdma")
    a = EnsembleEngine(method="pallas", comm="collective")
    b = EnsembleEngine(method="pallas", comm="fused")
    case = EnsembleCase(shape=(16, 16), nt=2, eps=2, k=1.0, dt=1e-4,
                       dh=0.02)
    chunk = [case]
    a.build_program(case.bucket_key(), chunk)
    b.build_program(case.bucket_key(), chunk)
    # the program keys differ in the comm slot: two engines differing
    # only in comm can never share compiled programs (since ISSUE 8 the
    # key ends ..., comm, stepper, stages)
    (ka,), (kb,) = a._programs.keys(), b._programs.keys()
    assert ka[:-3] == kb[:-3] and ka[-2:] == kb[-2:]
    assert (ka[-3], kb[-3]) == ("collective", "fused")
    # sibling() carries comm; the CPU fallback pins it back to
    # collective (the fused family is pallas-only and fallback chunks
    # run unsharded)
    assert b.sibling().comm == "fused"
    from nonlocalheatequation_tpu.serve.resilience import CpuFallback

    sib = CpuFallback(b)._sibling(2)
    assert sib.comm == "collective"


# -- obs wiring: /halo/* counters + halo.exchange span ----------------------


def test_halo_counters_and_span():
    from nonlocalheatequation_tpu.obs import trace as obs_trace
    from nonlocalheatequation_tpu.obs.metrics import REGISTRY

    mesh = make_mesh(2, 2)
    nt, eps = 3, 2
    f, _ = _pair_2d(mesh, 2, 2, 8, 8, nt=nt, eps=eps)
    f.test_init()
    ex0 = REGISTRY.counter("/halo/exchanges").value
    by0 = REGISTRY.counter("/halo/bytes").value
    tracer = obs_trace.Tracer()
    prev = obs_trace.set_tracer(tracer)
    try:
        f.do_work()
    finally:
        obs_trace.set_tracer(prev)
    # the counters follow the transport that actually RAN: comm='fused'
    # on CPU moves bands via the ppermute transport (interp split
    # kernel), so the collective plan's byte count is the honest one
    stats = ph.halo_stats((2, 2), (8, 8), eps, "collective", 8)
    assert (REGISTRY.counter("/halo/exchanges").value - ex0
            == nt * stats["messages"] * 4)
    assert (REGISTRY.counter("/halo/bytes").value - by0
            == nt * stats["bytes"] * 4)
    spans = [e for e in tracer.events if e["name"] == "halo.exchange"]
    assert len(spans) == 1
    assert spans[0]["args"]["comm"] == "fused"
    assert spans[0]["args"]["transport"] == "interp"
    assert spans[0]["args"]["devices"] == 4
    assert spans[0]["args"]["rounds"] == nt


def test_halo_stats_collective_counts_hops():
    # collective multi-hop: 2 hops each direction on x (4 messages),
    # 1 hop each direction on y (2): 6 ppermutes per round
    stats = ph.halo_stats((4, 2), (8, 8), 9, "collective", 8)
    assert stats["messages"] == 6
    assert stats["bytes"] == ph.collective_bytes((4, 2), (8, 8), 9, 8)


# -- CLI surface ------------------------------------------------------------


def test_cli_comm_fused_2d():
    from tests.test_cli import run_cli

    r = run_cli("solve2d_distributed",
                ["--nx", "8", "--ny", "8", "--npx", "4", "--npy", "2",
                 "--nt", "3", "--eps", "2", "--method", "pallas",
                 "--comm", "fused"])
    assert r.returncode == 0, r.stdout + r.stderr
    # elastic-path flags cannot ride the fused SPMD engine
    r = run_cli("solve2d_distributed",
                ["--comm", "fused", "--method", "pallas",
                 "--nbalance", "5"])
    assert r.returncode == 1
    assert "elastic" in r.stderr


def test_cli_comm_requires_distributed_3d():
    from tests.test_cli import run_cli

    r = run_cli("solve3d", ["--comm", "fused", "--method", "pallas"])
    assert r.returncode == 1
    assert "--distributed" in r.stderr
