"""Golden span-inventory (ISSUE 20): doc table <-> emitters <-> trace.

docs/architecture.md's "Span inventory (golden families)" table is a
CONTRACT, checked mechanically here graftlint-style:

* every documented family's emitter module(s) contain the literal span
  name, and the named "exercised by" test file exists;
* a source sweep over the package finds every literal duration-span
  emission (``obs_trace.span("..."``, ``tracer.complete("..."``,
  ``self._t_span("..."``) — the swept set and the documented set must
  be EQUAL, so a brand-new span cannot ship undocumented and a
  silently-dropped emitter cannot leave a stale doc row;
* every family marked **golden** must appear in the trace artifact of
  one traced chaos serve run: an injected first-attempt retry, a chunk
  that exhausts its retries onto the synchronous fallback route, a
  warm-boot reload from the AOT program store, the offline oracle
  comparison (bit-identity preserved under tracing), and one direct
  solver run.
"""

import json
import os
import re

import numpy as np

from nonlocalheatequation_tpu.obs import trace as obs_trace
from nonlocalheatequation_tpu.obs.trace import Tracer
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)
from nonlocalheatequation_tpu.serve.server import ServePipeline
from nonlocalheatequation_tpu.utils.faults import FaultPlan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "nonlocalheatequation_tpu")
DOC = os.path.join(REPO, "docs", "architecture.md")
ANCHOR = "### Span inventory (golden families)"

# literal duration-span emission points: the module-level/context
# manager form, an explicit tracer.complete with a literal name, and
# the serving pipeline's zero-extra-clock-read _t_span helper.  An
# ``instant(`` is an instant event, not a span family, by design.
EMIT_RE = re.compile(
    r'(?:\bspan|\bcomplete|_t_span)\(\s*"([a-z_]+\.[a-z_]+)"')


def parse_doc_table():
    """Rows of the inventory table: (family, cat, emitters, test, golden)."""
    text = open(DOC).read()
    assert ANCHOR in text, "span-inventory anchor missing from the doc"
    section = text.split(ANCHOR, 1)[1].split("\n## ", 1)[0]
    rows = []
    for line in section.splitlines():
        if not line.startswith("| `"):
            continue
        cols = [c.strip() for c in line.strip("|").split("|")]
        assert len(cols) == 5, f"malformed inventory row: {line!r}"
        family = cols[0].strip("`")
        emitters = re.findall(r"`([\w/]+\.py)`", cols[2])
        test = re.findall(r"`([\w/]+\.py)`", cols[3])
        assert emitters, f"no emitter modules in row: {line!r}"
        assert len(test) == 1, f"need exactly one test in row: {line!r}"
        rows.append((family, cols[1], emitters, test[0],
                     cols[4] == "golden"))
    assert rows, "span-inventory table has no rows"
    return rows


def sweep_source():
    """Every literal duration-span family emitted anywhere in the
    package, mapped to the repo-relative modules that emit it."""
    found = {}
    for dirpath, _dirs, files in os.walk(PKG):
        for fn in files:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, PKG)
            for name in EMIT_RE.findall(open(path).read()):
                found.setdefault(name, set()).add(rel)
    return found


def test_doc_table_and_emitters_cross_check():
    rows = parse_doc_table()
    swept = sweep_source()
    documented = {}
    for family, cat, emitters, test, _golden in rows:
        assert family not in documented, f"duplicate row for {family}"
        documented[family] = set(emitters)
        # the named test file must exist (a renamed suite must update
        # the table, or the "exercised by" claim rots)
        assert os.path.exists(os.path.join(REPO, test)), \
            f"{family}: exercising test {test} does not exist"
        for mod in emitters:
            src = open(os.path.join(PKG, mod)).read()
            assert f'"{family}"' in src, \
                f"{family}: documented emitter {mod} no longer emits it"
        assert cat, f"{family}: empty cat column"
    # set EQUALITY both ways: no undocumented span, no stale doc row
    assert set(documented) == set(swept), (
        f"doc table and source emitters disagree — undocumented: "
        f"{sorted(set(swept) - set(documented))}, stale rows: "
        f"{sorted(set(documented) - set(swept))}")
    for family, mods in swept.items():
        assert mods == documented[family], (
            f"{family}: doc lists {sorted(documented[family])}, "
            f"source emits from {sorted(mods)}")


def _chaos_cases(n, rng, nt=6):
    return [EnsembleCase(shape=(16, 16), nt=nt, eps=3.0 / 15, k=0.5,
                         dt=1e-5, dh=1.0 / 15, test=False,
                         u0=rng.normal(size=(16, 16)))
            for _ in range(n)]


def test_golden_families_appear_in_chaos_trace(tmp_path):
    golden = {f for f, _c, _e, _t, g in parse_doc_table() if g}
    rng = np.random.default_rng(7)
    store = str(tmp_path / "store")
    tr = Tracer(capacity=20_000, label="span-inventory")
    prev = obs_trace.set_tracer(tr)
    try:
        # chaos pass: the first chunk's first two attempts raise; the
        # two device-path failures open the breaker (threshold 2), so
        # the retry routes through the synchronous CPU fallback — every
        # case still serves, bit-identical to offline.  Programs land
        # in the AOT store (store.save)
        cases = _chaos_cases(3, rng)
        with ServePipeline(depth=1, window_ms=0.0, batch_sizes=(1,),
                           retries=2, backoff_ms=0.0, method="sat",
                           breaker_threshold=2,
                           faults=FaultPlan.parse("raise@0,raise@1"),
                           program_store=store, tracer=tr) as pipe:
            handles = [pipe.submit(c) for c in cases]
            pipe.drain()
            served = [np.asarray(h.result) for h in handles]
            assert all(r is not None for r in served)
            assert pipe.report.fallback_chunks >= 1, \
                "chaos plan never exhausted a chunk onto the fallback"
        # warm-boot pass: a fresh pipeline over the SAME store serves
        # without building (store.load)
        with ServePipeline(depth=1, window_ms=0.0, batch_sizes=(1,),
                           method="sat", program_store=store,
                           tracer=tr) as pipe:
            h = pipe.submit(_chaos_cases(1, np.random.default_rng(7))[0])
            pipe.drain()
            assert h.result is not None
        # offline oracle (ensemble.chunk): tracing must not perturb the
        # served numerics — bit-identity is the contract everywhere
        offline = EnsembleEngine(method="sat", batch_sizes=(1,),
                                 program_store=store).run(cases)
        for s, o in zip(served, offline):
            np.testing.assert_array_equal(s, np.asarray(o))
        # one direct solver run (solver.do_work)
        from nonlocalheatequation_tpu.models.solver2d import Solver2D

        s = Solver2D(16, 16, 4, eps=3, k=0.2, dt=0.001, dh=0.02,
                     backend="jit", method="conv")
        s.test_init()
        s.do_work()
    finally:
        obs_trace.set_tracer(prev)
    # the chaos-run trace ARTIFACT (not just the in-memory ring)
    artifact = tmp_path / "chaos_trace.json"
    tr.write(str(artifact))
    doc = json.load(open(artifact))
    families = {e["name"] for e in doc["traceEvents"]
                if e.get("ph") == "X"}
    missing = golden - families
    assert not missing, (
        f"golden span families missing from the chaos-run trace "
        f"artifact: {sorted(missing)} (captured: {sorted(families)})")
