"""Gang-scheduled elastic execution (parallel/gang.py).

The gang path must be indistinguishable from the per-device batched path in
VALUES (bit-identical: same assembly order, same op) while replacing
O(devices) host dispatch per step with one SPMD scan per stretch.
"""

import numpy as np
import pytest

import jax

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.parallel.elastic import ElasticSolver2D
from nonlocalheatequation_tpu.parallel import load_balance as lb
from nonlocalheatequation_tpu.utils.partition_map import default_assignment


def _run(gang, **kw):
    kw.setdefault("k", 1.0)
    kw.setdefault("dt", 1e-5)
    kw.setdefault("dh", 0.02)
    s = ElasticSolver2D(**kw)
    s.use_gang = gang
    s.test_init()
    s.do_work()
    return s


def test_gang_bit_identical_to_batched_path():
    a = _run(True, nx=10, ny=10, npx=5, npy=5, nt=24, eps=3, nlog=1000)
    b = _run(False, nx=10, ny=10, npx=5, npy=5, nt=24, eps=3, nlog=1000)
    assert np.array_equal(a.u, b.u)
    assert a.error_l2 == b.error_l2


def test_gang_matches_serial_oracle():
    a = _run(True, nx=10, ny=10, npx=5, npy=5, nt=24, eps=3, nlog=1000)
    o = Solver2D(50, 50, 24, eps=3, k=1.0, dt=1e-5, dh=0.02, backend="oracle")
    o.test_init()
    o.do_work()
    assert np.abs(a.u - o.u).max() < 1e-12


def test_gang_with_windows_and_rebalance_matches_oracle():
    """Measured windows + migrations interleave with gang stretches; the
    result still equals the oracle (migrations move bits, never recompute)."""
    a = _run(True, nx=10, ny=10, npx=5, npy=5, nt=24, eps=3, nlog=1000,
             nbalance=8)
    o = Solver2D(50, 50, 24, eps=3, k=1.0, dt=1e-5, dh=0.02, backend="oracle")
    o.test_init()
    o.do_work()
    assert np.abs(a.u - o.u).max() < 1e-12


def test_gang_model_telemetry_rebalance_still_fires():
    """With a model telemetry (no measured windows at all) the rebalance
    cadence must still fire between gang stretches — the slow device sheds
    tiles exactly as on the per-step path."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    tele = lb.WorkTelemetry(2, speed_factors=np.array([1.0, 3.0]))
    s = ElasticSolver2D(4, 4, 6, 6, nt=61, eps=2, nbalance=10,
                        k=0.2, dt=0.0005, dh=0.02,
                        assignment=default_assignment(6, 6, 2),
                        devices=jax.devices()[:2], telemetry=tele)
    s.test_init()
    s.do_work()
    counts = np.bincount(s.assignment.ravel(), minlength=2)
    assert counts[1] < counts[0], counts
    assert s.error_l2 / (24 * 24) <= 1e-6


def test_gang_imbalanced_assignment_and_logger_barriers():
    """A deliberately imbalanced placement (the reference's load_balance
    fixtures put 24 of 25 tiles on one node) runs through gang stretches,
    and logger barriers materialize consistent state mid-run."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    assignment = np.ones((5, 5), dtype=np.int64)
    assignment[0, 0] = 0
    logged = []
    s = ElasticSolver2D(5, 5, 5, 5, nt=12, eps=2, nlog=5, k=1.0, dt=1e-5,
                        dh=0.04, assignment=assignment,
                        devices=jax.devices()[:2],
                        logger=lambda t, u: logged.append((t, u.copy())))
    s.test_init()
    s.do_work()
    assert [t for t, _ in logged] == [0, 5, 10]
    o = Solver2D(25, 25, 12, eps=2, k=1.0, dt=1e-5, dh=0.04, backend="oracle")
    o.test_init()
    o.do_work()
    assert np.abs(s.u - o.u).max() < 1e-12
    # logged snapshots are the true mid-run states: re-run to t and compare
    o2 = Solver2D(25, 25, 6, eps=2, k=1.0, dt=1e-5, dh=0.04, backend="oracle")
    o2.test_init()
    o2.do_work()
    t5 = dict(logged)[5]
    assert np.abs(t5 - o2.u).max() < 1e-12


def test_gang_stretch_lengths_cover_plain_steps():
    """Stretch computation: windows excluded, logger steps end a stretch."""
    s = ElasticSolver2D(4, 4, 2, 2, nt=20, eps=2, nbalance=10,
                        measure_window=3, k=0.2, dt=0.0005, dh=0.02)
    # windows: {8,9,10} and {18,19} -> plain stretches [0..7], [11..17]
    assert s._gang_stretch_len(0, True) == 8
    assert s._gang_stretch_len(8, True) == 0
    assert s._gang_stretch_len(11, True) == 7
    assert s._gang_stretch_len(18, True) == 0
    s.logger = lambda t, u: None
    # nlog=5 (default): stretch from 0 ends after step 0 (logging barrier)
    assert s._gang_stretch_len(0, True) == 1
    assert s._gang_stretch_len(1, True) == 5  # 1..5, log at 5
    assert s._gang_stretch_len(6, True) == 2  # 6,7; 8 starts the window


def test_gang_opt_out_keeps_per_tile_general_path():
    """use_gang=False on the eps > tile regime keeps the per-tile
    rectangle-walk dispatch (the measured-window path) fully working."""
    s = _run(False, nx=4, ny=4, npx=5, npy=5, nt=8, eps=6, nlog=1000,
             dh=0.05)
    assert s._gang is None  # opted out: never constructed
    o = Solver2D(20, 20, 8, eps=6, k=1.0, dt=1e-5, dh=0.05, backend="oracle")
    o.test_init()
    o.do_work()
    assert np.abs(s.u - o.u).max() < 1e-12


def test_gang_checkpoint_resume_bit_identical(tmp_path):
    """Interrupted gang run resumes bit-for-bit (checkpoint barriers
    materialize the sharded state at the right steps)."""
    path = str(tmp_path / "gang.npz")
    full = _run(True, nx=10, ny=10, npx=2, npy=2, nt=16, eps=3, nlog=1000)
    part = ElasticSolver2D(10, 10, 2, 2, nt=16, eps=3, nlog=1000, k=1.0,
                           dt=1e-5, dh=0.02, checkpoint_path=path,
                           ncheckpoint=6)
    part.test_init()
    part.nt = 9  # "crash" after step 8 (checkpoint written at t=5)
    part.do_work()
    resumed = ElasticSolver2D(10, 10, 2, 2, nt=16, eps=3, nlog=1000, k=1.0,
                              dt=1e-5, dh=0.02, checkpoint_path=path,
                              ncheckpoint=6)
    resumed.test_init()
    resumed.resume(path)
    resumed.do_work()
    assert np.array_equal(full.u, resumed.u)


def test_gang_general_eps_exceeds_tile_bit_identical():
    """eps > tile edge now gang-schedules too (global-reassembly form);
    bit-identical to the per-tile rectangle-walk path."""
    def run(gang):
        s = ElasticSolver2D(4, 4, 5, 5, nt=10, eps=6, nlog=1000, k=1.0,
                            dt=1e-5, dh=0.05)
        s.use_gang = gang
        s.test_init()
        s.do_work()
        return s

    a, b = run(True), run(False)
    assert np.array_equal(a.u, b.u)
    assert a._gang is not None and a._gang.plan is not None
    o = Solver2D(20, 20, 10, eps=6, k=1.0, dt=1e-5, dh=0.05,
                 backend="oracle")
    o.test_init()
    o.do_work()
    assert np.abs(a.u - o.u).max() < 1e-12


def test_gang_general_reference_degenerate_case():
    """The reference's hardest ctest shape: 20x20 grid of 1x1 tiles with
    eps=10 — every tile's halo is the whole domain
    (tests/2d_distributed.txt; the nx <= eps warning path,
    src/2d_nonlocal_distributed.cpp:1202-1212, 1376-1379)."""
    s = ElasticSolver2D(1, 1, 20, 20, nt=10, eps=10, nlog=1000, k=1.0,
                        dt=1e-5, dh=0.05)
    s.test_init()
    s.do_work()
    assert s._gang is not None and s._gang.plan is not None  # gang ran
    o = Solver2D(20, 20, 10, eps=10, k=1.0, dt=1e-5, dh=0.05,
                 backend="oracle")
    o.test_init()
    o.do_work()
    assert np.abs(s.u - o.u).max() < 1e-12
    assert s.error_l2 / 400 <= 1e-6


def test_gang_general_with_rebalance_matches_oracle():
    """General-path gang + model-telemetry rebalance between stretches."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    tele = lb.WorkTelemetry(2, speed_factors=np.array([1.0, 2.0]))
    s = ElasticSolver2D(2, 2, 8, 8, nt=31, eps=3, nbalance=10, k=0.3,
                        dt=1e-5, dh=0.05, telemetry=tele,
                        devices=jax.devices()[:2])
    assert not s._use_fused  # eps 3 > tile edge 2
    s.test_init()
    s.do_work()
    o = Solver2D(16, 16, 31, eps=3, k=0.3, dt=1e-5, dh=0.05,
                 backend="oracle")
    o.test_init()
    o.do_work()
    assert np.abs(s.u - o.u).max() < 1e-12


def test_gang_pad_slots_stay_zero():
    """Devices with fewer tiles than T_max carry pad slots; the halo
    reasoning requires they remain EXACTLY zero through a run (their
    assembly reads only the zero slot)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    assignment = np.ones((4, 4), dtype=np.int64)
    assignment[0, 0] = 0  # device 0: 1 tile, device 1: 15 -> T_max = 15
    s = ElasticSolver2D(6, 6, 4, 4, nt=6, eps=2, nlog=1000, k=1.0,
                        dt=1e-5, dh=0.04, assignment=assignment,
                        devices=jax.devices()[:2])
    s.test_init()
    s.do_work()
    gang = s._gang
    assert gang is not None and gang.plan.t_max == 15
    state = np.asarray(gang._state)
    for d, own in gang.plan.order.items():
        for j in range(len(own), gang.plan.t_max):
            assert np.all(state[d, j] == 0.0), (d, j)


# -- superstep (communication-avoiding K*eps exchange per K steps) ----------


def test_gang_superstep_engages_and_matches_per_step_and_oracle():
    """K in {2, 3}: gang stretches exchange ONE K*eps-wide halo per K
    steps (gang.make_gang_run_superstep — the SPMD solver's schedule
    under arbitrary placement).  nt indivisible by K exercises the
    per-step remainder; values must stay 1e-12-close to the K=1 gang run
    and to the serial oracle (with the shift method and a stable dt they
    are bit-identical in practice — the levels see the same
    neighborhoods in the same reduction order)."""
    from nonlocalheatequation_tpu.parallel import gang as gang_mod

    built = []
    real = gang_mod.make_gang_run_superstep

    o = Solver2D(50, 50, 23, eps=3, k=1.0, dt=1e-5, dh=0.02,
                 backend="oracle")
    o.test_init()
    o.do_work()
    base = _run(True, nx=10, ny=10, npx=5, npy=5, nt=23, eps=3, nlog=1000)
    try:
        gang_mod.make_gang_run_superstep = (
            lambda *a, **kw: built.append(a[-1]) or real(*a, **kw))
        for K in (2, 3):
            a = _run(True, nx=10, ny=10, npx=5, npy=5, nt=23, eps=3,
                     nlog=1000, superstep=K)
            assert np.abs(a.u - base.u).max() < 1e-12
            assert np.abs(a.u - o.u).max() < 1e-12
            assert a.error_l2 / 2500 <= 1e-6
    finally:
        gang_mod.make_gang_run_superstep = real
    assert built == [2, 3], "superstep program did not engage"


def test_gang_superstep_with_barriers_windows_and_input_path():
    """Superstep under the full barrier mix (logging cadence, checkpoints,
    measured windows + rebalance): stretch lengths vary, remainders run
    per-step, and the result still equals the serial oracle.  The free-
    decay (input_init) path must agree with the K=1 run too."""
    from nonlocalheatequation_tpu.parallel import gang as gang_mod

    built = []
    real = gang_mod.make_gang_run_superstep
    gang_mod.make_gang_run_superstep = (
        lambda *a, **kw: built.append(1) or real(*a, **kw))
    logs = []
    try:
        a = _run(True, nx=10, ny=10, npx=5, npy=5, nt=24, eps=3, nlog=7,
                 nbalance=8, superstep=2, logger=lambda t, u: logs.append(t))
    finally:
        gang_mod.make_gang_run_superstep = real
    assert built, ("superstep never engaged under nbalance=8 — the "
                   "window-free runs between measured windows must form "
                   "K-blocks")
    o = Solver2D(50, 50, 24, eps=3, k=1.0, dt=1e-5, dh=0.02,
                 backend="oracle")
    o.test_init()
    o.do_work()
    assert np.abs(a.u - o.u).max() < 1e-12
    assert logs == [0, 7, 14, 21]

    rng = np.random.default_rng(5)
    u0 = rng.normal(size=(30, 30)).ravel()
    outs = {}
    for K in (1, 2):
        s = ElasticSolver2D(10, 10, 3, 3, nt=7, eps=3, k=1.0, dt=1e-5,
                            dh=0.02, superstep=K)
        s.input_init(u0)
        outs[K] = s.do_work()
    assert np.abs(outs[1] - outs[2]).max() < 1e-12


def test_gang_superstep_honesty_gates():
    """The flag must never silently run the per-step path: K*eps > tile
    edge is refused at construction, and opting out of gang scheduling
    under superstep raises instead of degrading."""
    with pytest.raises(ValueError, match="tile edge"):
        ElasticSolver2D(5, 5, 5, 5, nt=4, eps=2, k=1.0, dt=1e-5, dh=0.02,
                        superstep=3)
    s = ElasticSolver2D(10, 10, 3, 3, nt=4, eps=3, k=1.0, dt=1e-5,
                        dh=0.02, superstep=2)
    s.use_gang = False
    s.test_init()
    with pytest.raises(RuntimeError, match="gang executor"):
        s.do_work()
    # measure-everything mode (measure=True, no nbalance — the CLI's
    # --test_load_balance alone): every step is a measured window, so the
    # schedule could never engage — must refuse, not silently run per-step
    s2 = ElasticSolver2D(10, 10, 3, 3, nt=4, eps=3, k=1.0, dt=1e-5,
                         dh=0.02, superstep=2)
    s2.measure = True
    s2.test_init()
    with pytest.raises(RuntimeError, match="measured window"):
        s2.do_work()
    # nbalance <= measure_window measures EVERY step: no K-block could
    # ever form between windows — refused, not silently per-step
    s3 = ElasticSolver2D(10, 10, 3, 3, nt=12, eps=3, k=1.0, dt=1e-5,
                         dh=0.02, superstep=2, nbalance=5)
    s3.test_init()
    with pytest.raises(RuntimeError, match="window-free"):
        s3.do_work()


def test_gang_superstep_checkpoint_portable_across_schedules(tmp_path):
    """A checkpoint is SCHEDULE-AGNOSTIC state: written mid-trajectory by
    a superstep run it must resume under per-step (and vice versa) and
    land exactly where the uninterrupted run lands."""
    kw = dict(nx=10, ny=10, npx=3, npy=3, nt=12, eps=3, k=1.0, dt=1e-5,
              dh=0.02)
    straight = ElasticSolver2D(**kw)
    straight.test_init()
    u_ref = straight.do_work()

    for k_write, k_resume in ((2, 1), (1, 2), (2, 3)):
        ck = tmp_path / f"ck-{k_write}-{k_resume}.npz"
        w = ElasticSolver2D(checkpoint_path=str(ck), ncheckpoint=6,
                            superstep=k_write, **kw)
        w.test_init()
        w.nt = 9  # "crash" after step 8: the checkpoint on disk is t=6
        w.do_work()
        r = ElasticSolver2D(superstep=k_resume, **kw)
        r.test_init()
        r.resume(str(ck))
        assert r.t0 == 6
        u_res = r.do_work()
        d = np.abs(u_res - u_ref).max()
        assert d < 1e-12, f"K={k_write}->K={k_resume} resume drifts {d:.2e}"
