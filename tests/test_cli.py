"""CLI drivers: batch-test protocol ("Tests Passed" regex, the reference's
ctest contract, CMakeLists.txt:101-154) and normal runs."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_cli(module, args, stdin="", env_extra=None):
    env = {**os.environ, **(env_extra or {})}
    return subprocess.run(
        [sys.executable, "-m", f"nonlocalheatequation_tpu.cli.{module}",
         "--platform", "cpu", *args],
        input=stdin, capture_output=True, text=True, timeout=540, cwd=REPO,
        env=env,
    )


def test_1d_batch_small():
    r = run_cli("solve1d", ["--test_batch"], stdin="2\n50 45 5 1 0.001 0.02\n50 500 5 1 0.001 0.02\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr
    assert r.returncode == 0


def test_2d_batch_small():
    r = run_cli("solve2d", ["--test_batch"], stdin="1\n50 50 45 5 1 0.0005 0.02\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr


def test_2d_batch_failure_detected():
    # absurd dt makes the scheme blow up -> "Tests Failed" with exit code 1
    r = run_cli("solve2d", ["--test_batch"], stdin="1\n20 20 40 5 1 5.0 0.02\n")
    assert "Tests Failed" in r.stdout
    assert r.returncode == 1


def test_2d_fft_and_stepper_surface():
    # ISSUE 8: the spectral method + stepper tier on the CLI — an fft
    # batch passes the reference criterion; an rkc batch super-steps
    # 9x the reference dt in 5 steps to the same horizon and passes
    r = run_cli("solve2d", ["--test_batch", "--method", "fft"],
                stdin="1\n50 50 45 5 1 0.0005 0.02\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr
    r = run_cli("solve2d", ["--test_batch", "--stepper", "rkc",
                            "--superstep-stages", "8"],
                stdin="1\n50 50 5 5 1 0.0045 0.02\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr
    # the stability bound actually in force is printed for solo runs
    r = run_cli("solve2d", ["--test", "--stepper", "rkc", "--nt", "2",
                            "--cmp", "0"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "stability: dt bound in force" in r.stderr
    assert "rkc[s=8]" in r.stderr
    # rc 2: an explicit --dt past the selected stepper's model
    r = run_cli("solve2d", ["--test", "--stepper", "rkc",
                            "--superstep-stages", "2", "--dt", "0.1"])
    assert r.returncode == 2
    assert "exceeds the rkc[s=2] stability bound" in r.stderr
    # honesty refusals: expo needs fft; fft excludes the fused stencil
    # transport (the sharded spectral tier is collective-only, ISSUE 16)
    r = run_cli("solve2d", ["--test", "--stepper", "expo"])
    assert r.returncode == 1 and "requires --method fft" in r.stderr
    r = run_cli("solve3d", ["--test", "--method", "fft", "--distributed",
                            "--comm", "fused"])
    assert r.returncode == 1 and "pencil" in r.stderr
    # euler past its bound stays accepted (reference parity) with a loud
    # warning naming the bound
    r = run_cli("solve2d", ["--test", "--nt", "2", "--cmp", "0"])
    assert r.returncode == 0
    assert "WARNING: dt 0.0005 exceeds the forward-Euler" in r.stderr


def test_2d_batch_ensemble_mode():
    # --ensemble schedules the cases through serve/ensemble.py: same
    # pass criterion and output, one batched program per shape bucket
    # (the two same-shape cases here share one dispatch)
    r = run_cli("solve2d", ["--test_batch", "--ensemble"],
                stdin="3\n40 40 20 3 0.2 0.001 0.02\n"
                      "40 40 20 3 0.2 0.001 0.02\n"
                      "50 50 20 5 1 0.0005 0.02\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr
    assert r.returncode == 0
    assert "2 buckets" in r.stderr and "2 dispatches" in r.stderr
    # a blow-up case still fails the batch under the engine
    r = run_cli("solve2d", ["--test_batch", "--ensemble"],
                stdin="1\n20 20 40 5 1 5.0 0.02\n")
    assert "Tests Failed" in r.stdout
    assert r.returncode == 1
    # honesty: --ensemble outside --test_batch is refused
    r = run_cli("solve2d", ["--ensemble", "--test"])
    assert r.returncode == 1
    assert "requires" in r.stderr


def test_2d_batch_serve_mode():
    # --serve D streams the cases through the async serving pipeline
    # (serve/server.py): same pass criterion and output as --ensemble,
    # stderr carries the pipeline summary + one-line JSON metrics dump
    r = run_cli("solve2d", ["--test_batch", "--serve", "2"],
                stdin="3\n40 40 20 3 0.2 0.001 0.02\n"
                      "40 40 20 3 0.2 0.001 0.02\n"
                      "50 50 20 5 1 0.0005 0.02\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr
    assert r.returncode == 0
    assert "serve: 3 cases -> 2 buckets" in r.stderr
    metrics = [ln for ln in r.stderr.splitlines()
               if ln.startswith("{") and '"depth"' in ln]
    assert metrics, r.stderr
    import json

    m = json.loads(metrics[0])
    assert m["depth"] == 2 and m["cases"] == 3
    assert "request_latency_ms" in m and "occupancy" in m
    # a blow-up case still fails the batch under the pipeline
    r = run_cli("solve2d", ["--test_batch", "--serve", "2"],
                stdin="1\n20 20 40 5 1 5.0 0.02\n")
    assert "Tests Failed" in r.stdout
    assert r.returncode == 1
    # honesty refusals: --serve outside --test_batch; --serve + --ensemble
    r = run_cli("solve2d", ["--serve", "2", "--test"])
    assert r.returncode == 1 and "requires --test_batch" in r.stderr
    r = run_cli("solve2d", ["--test_batch", "--serve", "2", "--ensemble"])
    assert r.returncode == 1 and "drop --ensemble" in r.stderr


def test_2d_serve_quarantines_poison_case_and_serves_the_rest():
    # fault-tolerant serving surfaced through the CLI: a persistent
    # injected fault following case 1 (NLHEAT_FAULT_PLAN, the same env
    # knob the chaos suite uses) must quarantine exactly that case —
    # loudly, with the typed classification in stderr and the failure
    # telemetry in the metrics dump — and score it as a failed test
    # instead of killing the batch; disabling the CPU fallback keeps the
    # run on the pure retry+quarantine path
    import json

    r = run_cli("solve2d",
                ["--test_batch", "--serve", "2", "--serve-retries", "1",
                 "--serve-fallback", "0"],
                stdin="3\n40 40 20 3 0.2 0.001 0.02\n"
                      "40 40 20 3 0.2 0.001 0.02\n"
                      "50 50 20 5 1 0.0005 0.02\n",
                env_extra={"NLHEAT_FAULT_PLAN": "raise@c1x*"})
    assert "Tests Failed" in r.stdout, r.stdout + r.stderr
    assert r.returncode == 1
    assert "case 1 QUARANTINED" in r.stderr
    assert "classified 'error'" in r.stderr
    metrics = [ln for ln in r.stderr.splitlines()
               if ln.startswith("{") and '"resilience"' in ln]
    assert metrics, r.stderr
    m = json.loads(metrics[0])
    assert [q["case"] for q in m["resilience"]["quarantined"]] == [1]
    assert m["resilience"]["breaker"]["state"] == "disabled"


def test_serve_supervision_flag_refusals():
    r = run_cli("solve2d", ["--test_batch", "--serve", "2",
                            "--serve-retries", "-1"], stdin="0\n")
    assert r.returncode == 1 and "--serve-retries" in r.stderr
    r = run_cli("solve2d", ["--test_batch", "--serve", "2",
                            "--serve-deadline-ms", "-5"], stdin="0\n")
    assert r.returncode == 1 and "--serve-deadline-ms" in r.stderr
    r = run_cli("solve2d", ["--test_batch", "--serve", "2",
                            "--serve-nan-policy", "bogus"], stdin="0\n")
    assert r.returncode == 2 and "--serve-nan-policy" in r.stderr
    # a bool-flag typo must be a loud rc-2 refusal, never a silent
    # False that quietly disables the CPU fallback it meant to enable
    r = run_cli("solve2d", ["--test_batch", "--serve", "2",
                            "--serve-fallback", "ture"], stdin="0\n")
    assert r.returncode == 2 and "--serve-fallback" in r.stderr


def test_listen_flag_refusals():
    # ISSUE 10: the front-door flags' honesty checks — --listen excludes
    # the stdin-driven modes, --replicas needs --listen, and the serial-
    # engine rule carries over from --serve/--ensemble
    r = run_cli("solve2d", ["--listen", "0", "--test_batch"], stdin="0\n")
    assert r.returncode == 1 and "--test_batch" in r.stderr
    r = run_cli("solve2d", ["--listen", "0", "--test"])
    assert r.returncode == 1 and "--test belongs" in r.stderr
    r = run_cli("solve2d", ["--replicas", "2"], stdin="")
    assert r.returncode == 1 and "--replicas" in r.stderr \
        and "--listen" in r.stderr
    r = run_cli("solve2d", ["--listen", "0", "--replicas", "0"], stdin="")
    assert r.returncode == 1 and "N >= 1" in r.stderr
    r = run_cli("solve2d", ["--listen", "99999"], stdin="")
    assert r.returncode == 1 and "[0, 65535]" in r.stderr
    r = run_cli("solve3d", ["--listen", "0", "--distributed"], stdin="")
    assert r.returncode == 1 and "--distributed" in r.stderr
    # ISSUE 12: the fleet-transport + sharded-tier flags' honesty checks
    r = run_cli("solve2d", ["--transport", "tcp"], stdin="")
    assert r.returncode == 1 and "--transport" in r.stderr \
        and "--listen" in r.stderr
    r = run_cli("solve2d", ["--listen", "0", "--worker-token", "s"],
                stdin="")
    assert r.returncode == 1 and "--transport tcp" in r.stderr
    r = run_cli("solve2d", ["--worker-token", "s"], stdin="")
    assert r.returncode == 1 and "--listen" in r.stderr
    r = run_cli("solve2d", ["--listen", "0", "--shard-threshold", "-1"],
                stdin="")
    assert r.returncode == 1 and "--shard-threshold" in r.stderr
    r = run_cli("solve2d", ["--listen", "0", "--gang-devices", "4"],
                stdin="")
    assert r.returncode == 1 and "--shard-threshold" in r.stderr
    # the sharded case class is the 2D flagship tier: the 1D/3D CLIs
    # refuse the flag instead of silently never engaging it
    r = run_cli("solve1d", ["--listen", "0", "--shard-threshold", "64"],
                stdin="")
    assert r.returncode == 1 and "solve2d" in r.stderr
    r = run_cli("solve3d", ["--listen", "0", "--shard-threshold", "64"],
                stdin="")
    assert r.returncode == 1 and "solve2d" in r.stderr
    r = run_cli("solve2d", ["--listen", "0", "--transport", "bogus"],
                stdin="")
    assert r.returncode == 2 and "--transport" in r.stderr
    # ISSUE 20: the SLO audit flag needs the serving front door
    r = run_cli("solve2d", ["--slo", "1"], stdin="")
    assert r.returncode == 1 and "--slo" in r.stderr \
        and "--listen" in r.stderr
    r = run_cli("solve2d", ["--listen", "0", "--slo", "2"], stdin="")
    assert r.returncode == 2 and "--slo" in r.stderr


def test_listen_serves_http_and_stops_on_stdin_eof():
    # ISSUE 10 end to end on the CLI surface: --listen starts the
    # ingress over a worker fleet, serves a POSTed case bit-identically
    # to the offline engine, and exits 0 when stdin reaches EOF
    import json as _json
    import re
    import urllib.request

    import numpy as np

    from nonlocalheatequation_tpu.serve.ensemble import (
        EnsembleCase,
        EnsembleEngine,
    )

    rng = np.random.default_rng(7)
    u0 = rng.normal(size=(12, 12))
    want = EnsembleEngine(method="conv").run(
        [EnsembleCase(shape=(12, 12), nt=3, eps=2, k=1.0, dt=1e-5,
                      dh=1.0 / 12, test=False, u0=u0)])[0]
    proc = subprocess.Popen(
        [sys.executable, "-m", "nonlocalheatequation_tpu.cli.solve2d",
         "--listen", "0", "--platform", "cpu", "--x64", "1",
         "--method", "conv"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, cwd=REPO)
    try:
        port = None
        for _ in range(400):
            line = proc.stderr.readline()
            m = re.search(r"http://127.0.0.1:(\d+)/v1/cases", line)
            if m:
                port = int(m.group(1))
                break
        assert port, "ingress endpoint line never printed"
        body = dict(shape=[12, 12], nt=3, eps=2, k=1.0, dt=1e-5,
                    dh=1.0 / 12, u0=u0.tolist())
        r = urllib.request.urlopen(urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/cases",
            _json.dumps(body).encode()))
        case_id = _json.load(r)["id"]
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v1/cases/{case_id}?wait=1")
        assert _json.load(r)["status"] == "done"
        r = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v1/cases/{case_id}/result")
        res = _json.load(r)
        assert np.array_equal(
            np.asarray(res["values"]).reshape(res["shape"]), want)
    finally:
        proc.stdin.close()  # EOF = shutdown
        rc = proc.wait(timeout=120)
    err = proc.stderr.read()
    assert rc == 0, err
    assert "router:" in err and '"cases": 1' in err


def test_serve_nan_policy_serve_restores_diverged_result_contract():
    # --serve-nan-policy serve: a deterministically divergent case is a
    # SERVED result judged by the oracle criterion (PR 3's contract) —
    # it fails the batch with a real error number, burns no retries, and
    # is NOT quarantined
    r = run_cli("solve2d", ["--test_batch", "--serve", "2",
                            "--serve-nan-policy", "serve"],
                stdin="1\n20 20 40 5 1 5.0 0.02\n")
    assert "Tests Failed" in r.stdout
    assert r.returncode == 1
    assert "QUARANTINED" not in r.stderr
    assert '"quarantined": []' in r.stderr


def test_serve_truncated_stream_still_refused_loudly():
    # the streaming intake (iter_batch_cases) must keep PR 2's refusal
    # verbatim: case index + expected token count, no stack trace
    r = run_cli("solve2d", ["--test_batch", "--serve", "2"],
                stdin="2\n40 40 20 3 0.2 0.001 0.02\n40 40 20\n")
    assert r.returncode == 1
    assert "batch case 1" in r.stderr and "7 tokens" in r.stderr
    assert "Traceback" not in r.stderr


def test_iter_batch_cases_refusal_shapes():
    # in-process shapes of the streaming parser's refusals — verbatim
    # parse_batch_cases messages, fired at the failing row
    import io

    import pytest

    from nonlocalheatequation_tpu.cli.common import iter_batch_cases

    def read7(toks, pos):
        v = toks[pos:pos + 7]
        return tuple(float(x) for x in v), pos + 7

    ok = list(iter_batch_cases(read7, 7,
                               io.StringIO("1\n1 2 3 4 5 6 7\n")))
    assert len(ok) == 1
    # tokens may span lines arbitrarily, like the EOF tokenizer
    ok = list(iter_batch_cases(read7, 7,
                               io.StringIO("2 1 2 3\n4 5 6 7 8\n"
                                           "9 10 11 12 13 14\n")))
    assert len(ok) == 2
    with pytest.raises(SystemExit, match="empty"):
        list(iter_batch_cases(read7, 7, io.StringIO("")))
    with pytest.raises(SystemExit, match="not an integer"):
        list(iter_batch_cases(read7, 7, io.StringIO("lots\n")))
    with pytest.raises(SystemExit, match="declares -1"):
        list(iter_batch_cases(read7, 7, io.StringIO("-1\n")))
    with pytest.raises(SystemExit, match="case 1.*truncated"):
        list(iter_batch_cases(read7, 7,
                              io.StringIO("2 1 2 3 4 5 6 7 8 9\n")))
    with pytest.raises(SystemExit, match="case 0.*malformed"):
        list(iter_batch_cases(read7, 7,
                              io.StringIO("1 1 2 xx 4 5 6 7\n")))
    # streaming semantics: earlier good rows are yielded BEFORE a later
    # bad row refuses (the serving pipeline has already scheduled them)
    it = iter_batch_cases(read7, 7,
                          io.StringIO("2 1 2 3 4 5 6 7 8 9\n"))
    assert next(it) == (1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0)
    with pytest.raises(SystemExit, match="truncated"):
        next(it)


def test_batch_malformed_stdin_refused_loudly():
    # ISSUE 2 satellite: a truncated/malformed token stream used to die
    # with a bare IndexError; it must refuse with the case index and the
    # expected token count, before any solve runs
    r = run_cli("solve2d", ["--test_batch"],
                stdin="2\n50 50 45 5 1 0.0005\n")
    assert r.returncode == 1
    assert "batch case 0" in r.stderr and "7 tokens" in r.stderr
    assert "Traceback" not in r.stderr


def test_parse_batch_cases_refusal_shapes():
    # the in-process shapes of the same refusal (parse_batch_cases is
    # what every batch CLI now routes through)
    import pytest

    from nonlocalheatequation_tpu.cli.common import parse_batch_cases

    def read7(toks, pos):
        v = toks[pos:pos + 7]
        return tuple(float(x) for x in v), pos + 7

    ok = parse_batch_cases(read7, "1 1 2 3 4 5 6 7".split(), row_tokens=7)
    assert len(ok) == 1
    with pytest.raises(SystemExit, match="empty"):
        parse_batch_cases(read7, [], row_tokens=7)
    with pytest.raises(SystemExit, match="not an integer"):
        parse_batch_cases(read7, ["lots"], row_tokens=7)
    with pytest.raises(SystemExit, match="case 1.*truncated"):
        parse_batch_cases(
            read7, "2 1 2 3 4 5 6 7 8 9".split(), row_tokens=7)
    with pytest.raises(SystemExit, match="case 0.*malformed"):
        parse_batch_cases(
            read7, "1 1 2 xx 4 5 6 7".split(), row_tokens=7)


def test_async_batch_degenerate_tiles():
    # np=20 with nx=1: tile smaller than horizon (reference case row 9)
    r = run_cli("solve2d_async", ["--test_batch"], stdin="1\n1 1 20 40 5 0.2 0.001 0.02\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr


def test_distributed_batch():
    r = run_cli("solve2d_distributed", ["--test_batch"],
                stdin="1\n25 25 2 2 45 5 1 0.0005 0.02\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr


def test_distributed_superstep_flag():
    # the communication-avoiding schedule through the CLI surface — on the
    # SPMD path AND (since the gang superstep landed) the elastic path;
    # the honesty guard refuses only where the schedule cannot engage
    r = run_cli("solve2d_distributed", ["--test_batch", "--superstep", "3"],
                stdin="1\n25 25 2 2 45 5 1 0.0005 0.02\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr
    # nbalance 8 leaves 8 - min(5, 8) = 3 window-free steps per cadence:
    # the K=2 gang superstep genuinely engages
    r = run_cli("solve2d_distributed",
                ["--superstep", "2", "--nbalance", "8", "--nt", "17"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "l2:" in r.stdout
    # nbalance 5 measures EVERY step (measure_window = min(5, nbalance)):
    # the schedule could never engage — refused, not silently per-step
    r = run_cli("solve2d_distributed",
                ["--superstep", "2", "--nbalance", "5", "--nt", "12"])
    assert r.returncode != 0
    assert "window-free" in (r.stdout + r.stderr)
    r = run_cli("solve2d_distributed",
                ["--superstep", "9", "--nbalance", "5", "--nt", "2"])
    assert r.returncode != 0
    assert "tile edge" in (r.stdout + r.stderr)


def test_2d_normal_run_prints_error_and_timing():
    r = run_cli("solve2d", ["--test", "--cmp", "false", "--nt", "5",
                            "--nx", "20", "--ny", "20"])
    assert "l2:" in r.stdout and "linfinity:" in r.stdout
    assert "OS_Threads" in r.stdout  # timing header
    assert r.stdout.startswith("2d_nonlocal (")  # version banner


def test_distributed_with_partition_map(tmp_path):
    # the reference reads tile sizes + dh from the map file (--file)
    mapfile = tmp_path / "map.txt"
    mapfile.write_text("10 10 2 2 0.02\n0 0 0\n0 1 0\n1 0 0\n1 1 0\n")
    r = run_cli("solve2d_distributed",
                ["--file", str(mapfile), "--nt", "5", "--cmp", "false"])
    assert "l2:" in r.stdout, r.stdout + r.stderr


def test_flagship_chain_decompose_map_balance_superstep(tmp_path):
    """The reference's full flagship chain, end to end through the CLI
    surface: decompose a GMSH mesh into a partition map, then solve with
    that placement + periodic rebalancing + the (r5) communication-
    avoiding gang superstep, and report the balance acceptance."""
    from nonlocalheatequation_tpu.cli import decompose

    mapfile = str(tmp_path / "map.txt")
    rc = decompose.main([os.path.join(REPO, "data/10x10.msh"), mapfile,
                         "2", "--sx", "2", "--sy", "2"])
    assert rc in (0, None) and os.path.exists(mapfile)
    # 5x5 tiles of 2x2 -> eps=1 keeps K*eps <= tile for the K=2 superstep
    r = run_cli("solve2d_distributed",
                ["--file", mapfile, "--nt", "17", "--eps", "1",
                 "--nbalance", "8", "--superstep", "2",
                 "--test_load_balance", "--cmp", "false"])
    assert r.returncode == 0, r.stdout + r.stderr
    l2 = float(r.stdout.split("l2:")[1].split()[0])
    assert l2 / 100 <= 1e-6, f"L2/N contract violated: {l2 / 100}"
    assert "balance" in r.stdout.lower()  # the acceptance report printed


def test_1d_results_and_input_init():
    vals = " ".join(["0.5"] * 10)
    r = run_cli("solve1d", ["--nx", "10", "--nt", "3", "--results"], stdin=vals)
    assert "S[0] =" in r.stdout


def test_unstructured_cli_on_gmsh_mesh(tmp_path):
    """Framework extension: solve directly on a .msh node set; manufactured
    contract + .vtu output round-trip."""
    import numpy as np

    from nonlocalheatequation_tpu.cli import solve_unstructured
    from nonlocalheatequation_tpu.utils.vtu import read_vtu_point_data

    vtu = str(tmp_path / "u.vtu")
    rc = solve_unstructured.main([
        "--mesh", os.path.join(REPO, "data/10x10.msh"), "--test", "--nt", "10",
        "--vtu", vtu, "--no-header",
    ])
    assert rc == 0
    data = read_vtu_point_data(vtu)
    assert data["Temperature"].shape == (121,)  # 11x11 nodes
    assert np.isfinite(data["Temperature"]).all()


def test_unstructured_cli_sharded(capsys):
    import jax

    from nonlocalheatequation_tpu.cli import solve_unstructured

    ndev = min(4, len(jax.devices()))
    rc = solve_unstructured.main([
        "--mesh", os.path.join(REPO, "data/50x50.msh"), "--test", "--nt", "5",
        "--devices", str(ndev), "--no-header",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert f"sharded over {ndev} devices" in out or ndev == 1
    assert "error_l2/N" in out


def test_reference_workflow_chain(tmp_path):
    """The reference's full documented workflow, end to end (README.md:45-72):
    GMSH mesh -> decomposition tool -> partition map -> distributed solve
    with --file + manufactured test -> the L2/N <= 1e-6 contract."""
    mapfile = str(tmp_path / "map.txt")
    # the decompose tool is pure host code (no backend, no --platform flag)
    r = subprocess.run(
        [sys.executable, "-m", "nonlocalheatequation_tpu.cli.decompose",
         os.path.join(REPO, "data/10x10.msh"), mapfile, "4",
         "--sx", "5", "--sy", "5"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    header = open(mapfile).read().splitlines()
    # "mx/npx my/npy npx npy dh" + one row per tile (reference map format,
    # src/domain_decomposition.cpp:31-50)
    assert header[0].split() == ["5", "5", "2", "2", "0.1"]
    assert len(header) == 1 + 4
    owners = {int(row.split()[2]) for row in header[1:]}
    assert owners <= {0, 1, 2, 3} and len(owners) > 1

    r = run_cli("solve2d_distributed",
                ["--file", mapfile, "--nt", "10", "--test", "true",
                 "--cmp", "false"])
    assert r.returncode == 0, r.stdout + r.stderr
    l2 = float(r.stdout.split("l2:")[1].split()[0])
    npoints = 10 * 10
    assert l2 / npoints <= 1e-6, f"L2/N contract violated: {l2 / npoints}"


# -- observability flags (obs/, ISSUE 5) ------------------------------------
def test_metrics_out_writes_serve_dump_atomically(tmp_path):
    # --metrics-out persists the same one-line dump --serve prints to
    # stderr; the file parses and agrees on the headline counters
    import json

    out = tmp_path / "metrics.json"
    r = run_cli("solve2d", ["--test_batch", "--serve", "2",
                            "--metrics-out", str(out)],
                stdin="2\n32 32 10 5 1 0.001 0.03125\n"
                      "32 32 10 5 1 0.001 0.03125\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr
    assert f"metrics written to {out}" in r.stderr
    m = json.loads(out.read_text())
    assert m["cases"] == 2 and "resilience" in m
    # no stranded tmp file from the atomic-write discipline
    assert list(tmp_path.iterdir()) == [out]


def test_metrics_out_unwritable_path_refused_before_solve(tmp_path):
    # a typo'd path must refuse up front (exit 1, loud), not discard the
    # run's metrics at the final write
    bad = tmp_path / "no" / "such" / "dir" / "m.json"
    r = run_cli("solve2d", ["--test_batch", "--serve", "2",
                            "--metrics-out", str(bad)], stdin="0\n")
    assert r.returncode == 1
    assert "not writable" in r.stderr
    assert "Tests" not in r.stdout  # refused before any solve ran


def test_metrics_out_solo_run_snapshots_solve_gauges(tmp_path):
    import json

    out = tmp_path / "m.json"
    r = run_cli("solve1d", ["--test", "--nx", "32", "--nt", "10",
                            "--metrics-out", str(out)])
    assert r.returncode == 0, r.stdout + r.stderr
    m = json.loads(out.read_text())
    assert m["/solve{1d}/points"] == 32 and m["/solve{1d}/steps"] == 10
    assert m["/solve{1d}/elapsed-s"] > 0
    assert m["/solve{1d}/error-l2"] <= 32 * 1e-6


def test_trace_flag_writes_perfetto_loadable_host_trace(tmp_path):
    # --trace DIR: the host-side span timeline lands as
    # DIR/host_trace.json (Chrome trace-event JSON) next to the
    # jax.profiler capture tree
    import json

    tdir = tmp_path / "tr"
    r = run_cli("solve2d", ["--test_batch", "--serve", "2",
                            "--trace", str(tdir)],
                stdin="2\n32 32 10 5 1 0.001 0.03125\n"
                      "32 32 10 5 1 0.001 0.03125\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr
    doc = json.loads((tdir / "host_trace.json").read_text())
    names = {ev["name"] for ev in doc["traceEvents"]}
    assert {"serve.close", "serve.build", "serve.dispatch",
            "serve.fetch"} <= names
    for ev in doc["traceEvents"]:
        assert ev["ph"] in ("X", "i", "C") and "ts" in ev
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
    # the jax.profiler device capture landed in the SAME directory
    assert any(p.name != "host_trace.json" for p in tdir.rglob("*")
               if p.is_file())


def test_metrics_port_out_of_range_refused():
    r = run_cli("solve2d", ["--test_batch", "--serve", "2",
                            "--metrics-port", "99999"], stdin="0\n")
    assert r.returncode == 1
    assert "--metrics-port" in r.stderr


def test_metrics_out_directory_path_refused_up_front(tmp_path):
    # a directory passes the sibling-file probe but the final
    # os.replace cannot land on it — must refuse before the solve
    r = run_cli("solve2d", ["--test_batch", "--serve", "2",
                            "--metrics-out", str(tmp_path)], stdin="0\n")
    assert r.returncode == 1
    assert "is a directory" in r.stderr
    assert "Tests" not in r.stdout


def test_trace_plus_profile_conflict_refused(tmp_path):
    # jax.profiler cannot nest: --trace already captures the device
    # timeline, so a combined --profile would silently vanish — refuse
    r = run_cli("solve2d", ["--test", "--trace", str(tmp_path / "tr"),
                            "--profile", str(tmp_path / "prof")])
    assert r.returncode == 1
    assert "--trace already captures" in r.stderr


def test_metrics_out_midrun_write_failure_never_masks_solve_error(tmp_path):
    # the finally-block refusal (SystemExit 1) only fires when the solve
    # body exited cleanly: a solve exception must propagate with its
    # own traceback even when the --metrics-out write also fails
    import shutil
    import types

    from nonlocalheatequation_tpu.cli.common import obs_session

    sub = tmp_path / "sub"
    sub.mkdir()
    args = types.SimpleNamespace(trace=None, metrics_port=None,
                                 metrics_out=str(sub / "m.json"))
    with pytest.raises(RuntimeError, match="solve blew up"):
        with obs_session(args):
            shutil.rmtree(sub)  # the mid-run filesystem change
            raise RuntimeError("solve blew up")
    # and the clean-body path still refuses loudly
    with pytest.raises(SystemExit) as ei:
        with obs_session(args):
            pass
    assert ei.value.code == 1


def test_ensemble_metrics_out_records_engine_report(tmp_path):
    import json

    out = tmp_path / "m.json"
    r = run_cli("solve2d", ["--test_batch", "--ensemble",
                            "--metrics-out", str(out)],
                stdin="2\n32 32 10 5 1 0.001 0.03125\n"
                      "32 32 10 5 1 0.001 0.03125\n")
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr
    m = json.loads(out.read_text())
    assert m["cases"] == 2 and m["dispatches"] == 1
