"""Fleet-wide distributed tracing + flight recorder (ISSUE 11).

What these tests pin, on the CPU/f64 suite:

* :class:`TraceContext`: wire/header round trips, tolerant decode (a
  malformed frame field costs the trace, never the case), context
  install/stamping (every event a tracer emits under an installed
  context carries the originating request's trace id — the disabled
  path never reads it);
* :func:`merge_chrome_traces`: DETERMINISTIC clock alignment on
  injected clock_sync pairs — two processes whose monotonic epochs
  differ by a known offset merge into one ordered timeline with pid =
  replica and process_name records; flow events survive;
* the flight recorder: bounded ring + lifetime-exact count, postmortem
  dump contents (events, registry snapshot, in-flight ledger), the
  flush hook (EventLog lines are on disk before the postmortem), the
  ``NLHEAT_FLIGHT_DIR`` opt-in, injected-clock dump naming;
* the EventLog ``seq`` bugfix + :func:`merge_event_streams`: per-process
  total order by seq survives cross-process clock skew;
* fleet-scrape staleness: a dead replica's absorbed ``/replica{r}``
  gauges are labeled stale inside the window and DROPPED after it;
* the retrace watchdog: ``arm_steady_state`` + a post-warm-up build ->
  ``/store/steady-state-builds`` + a loud warning;
* the GOLDEN end-to-end trace: a 2-replica routed run through the HTTP
  ingress with one injected retry (worker fault plan) and one ``die@``
  kill — the merged artifact is schema-valid, every stamped span's
  trace id chains to an ingress-minted request, flow events connect
  across pids (ingress start -> router step -> worker finish), served
  results stay bit-identical to offline, and the postmortem names the
  killed replica's orphaned cases and each re-route decision.  The
  4-replica chaos acceptance run is the slow-marked twin.
"""

import json
import os
import time
import urllib.request

import numpy as np
import pytest

import jax

from nonlocalheatequation_tpu.obs import flightrec
from nonlocalheatequation_tpu.obs import trace as obs_trace
from nonlocalheatequation_tpu.obs.export import (
    EventLog,
    merge_event_streams,
    read_jsonl,
)
from nonlocalheatequation_tpu.obs.metrics import MetricsRegistry
from nonlocalheatequation_tpu.obs.trace import (
    TraceContext,
    Tracer,
    merge_chrome_traces,
)
from nonlocalheatequation_tpu.serve.ensemble import (
    EnsembleCase,
    EnsembleEngine,
)
from nonlocalheatequation_tpu.serve.http import IngressServer
from nonlocalheatequation_tpu.serve.router import ReplicaRouter
from nonlocalheatequation_tpu.serve.server import ServePipeline
from nonlocalheatequation_tpu.utils.faults import FaultPlan

assert jax.config.jax_enable_x64  # the oracle contract (conftest forces it)

PHASES = ("X", "i", "C", "s", "t", "f", "M")


def _check_schema(events):
    """Chrome trace-event schema incl. flow ('s'/'t'/'f') and metadata
    ('M') records — the fields Perfetto actually keys on."""
    assert events, "no events recorded"
    for ev in events:
        assert ev["ph"] in PHASES, ev
        assert isinstance(ev["name"], str) and ev["name"]
        assert isinstance(ev["pid"], int)
        if ev["ph"] == "M":
            continue
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        if ev["ph"] in ("s", "t", "f"):
            assert isinstance(ev["id"], str) and ev["id"]
        if ev["ph"] == "f":
            assert ev["bp"] == "e"  # bind-enclosing: ties to the slice


def make_cases(n, grid=16, nt=4, buckets=2, seed=0):
    rng = np.random.default_rng(seed)
    return [EnsembleCase(shape=(grid, grid), nt=nt + (i % buckets), eps=2,
                         k=1.0, dt=1e-5, dh=1.0 / grid, test=False,
                         u0=rng.normal(size=(grid, grid)))
            for i in range(n)]


# ---------------------------------------------------------------------------
# TraceContext: wire forms + context stamping
# ---------------------------------------------------------------------------


def test_trace_context_wire_and_header_round_trip():
    ctx = TraceContext.mint(request=7)
    assert len(ctx.trace_id) == 16 and ctx.request == 7
    back = TraceContext.from_wire(ctx.to_wire())
    assert (back.trace_id, back.span_id, back.request) == \
        (ctx.trace_id, ctx.span_id, ctx.request)
    hdr = TraceContext("abc123", "span9", 4).to_header()
    assert hdr == "abc123:span9:4"
    h = TraceContext.from_header(hdr)
    assert (h.trace_id, h.span_id, h.request) == ("abc123", "span9", 4)
    assert TraceContext.from_header("bare").trace_id == "bare"
    # tolerant decode: garbage costs the trace, never raises
    assert TraceContext.from_wire(None) is None
    assert TraceContext.from_wire(()) is None
    assert TraceContext.from_wire(("t", None, "not-an-int")) is None
    assert TraceContext.from_header("") is None
    assert TraceContext.from_header(":x:") is None
    # distinct mints: the id is the fleet-wide identity
    assert TraceContext.mint().trace_id != TraceContext.mint().trace_id


def test_installed_context_stamps_every_emitted_event():
    tr = Tracer(clock=iter(np.arange(1, 100) * 1e-3).__next__)
    ctx = TraceContext("feedfacefeedface", request=3)
    prev = obs_trace.set_context(ctx)
    try:
        tr.complete("serve.build", 0.001, 0.002, cat="serve", chunk=0)
        tr.instant("serve.dispatch", chunk=0)
        tr.flow("request", "finish", ctx.trace_id, req=3)
        # counter events are EXEMPT from the stamp: every args key of a
        # 'C' event is a plotted Perfetto series, and a trace/req stamp
        # would graft bogus tracks onto e.g. the inflight counter
        tr.counter("serve.inflight", inflight=2)
    finally:
        obs_trace.set_context(prev)
    counter = tr.events[-1]
    assert counter["ph"] == "C" and counter["args"] == {"inflight": 2}
    tr.complete("outside", 0.003, 0.004)  # context restored: no stamp
    evs = list(tr.events)
    for ev in evs[:3]:
        assert ev["args"]["trace"] == "feedfacefeedface"
        assert ev["args"]["req"] == 3
    assert "args" not in evs[4]
    # explicit args of the same name win over the stamp
    prev = obs_trace.set_context(ctx)
    try:
        tr.complete("explicit", 0.005, 0.006, trace="other")
    finally:
        obs_trace.set_context(prev)
    assert tr.events[-1]["args"]["trace"] == "other"
    assert obs_trace.current_context() is None  # suite default restored


# ---------------------------------------------------------------------------
# the merge: deterministic clock alignment on injected sync pairs
# ---------------------------------------------------------------------------


def test_merge_aligns_injected_clock_offsets_and_remaps_pids():
    # two processes with DIFFERENT monotonic epochs observing one wall
    # clock: replica 0 booted at monotonic 100 (wall 1000), replica 1
    # at monotonic 5 (wall 1000.050) — events interleave by wall time
    a = Tracer(clock=iter([100.010, 100.020, 100.100]).__next__,
               pid=111, label="replica 0", replica=0,
               clock_sync={"monotonic": 100.0, "wall": 1000.0})
    b = Tracer(clock=iter([5.000, 5.025]).__next__,
               pid=222, label="replica 1", replica=1,
               clock_sync={"monotonic": 5.0, "wall": 1000.050})
    a.complete("a0", a._clock(), a._clock())  # wall 1000.010 -> .020
    b.complete("b0", b._clock(), b._clock())  # wall 1000.050 -> .075
    a.instant("a1")                           # wall 1000.100
    merged = merge_chrome_traces([a.chrome_trace(), b.chrome_trace()])
    evs = merged["traceEvents"]
    _check_schema(evs)
    names = [e["name"] for e in evs if e["ph"] != "M"]
    assert names == ["a0", "b0", "a1"]  # wall order, not per-doc order
    # earliest event re-based to 0; offsets exact (microseconds)
    by = {e["name"]: e for e in evs if e["ph"] != "M"}
    assert by["a0"]["ts"] == pytest.approx(0.0, abs=0.5)
    assert by["b0"]["ts"] == pytest.approx(40_000.0, abs=0.5)
    assert by["a1"]["ts"] == pytest.approx(90_000.0, abs=0.5)
    # pid = replica id in the merged view, with process_name records
    assert by["a0"]["pid"] == 0 and by["b0"]["pid"] == 1
    meta = [e for e in evs if e["ph"] == "M"]
    assert {(m["pid"], m["args"]["name"]) for m in meta} == \
        {(0, "replica 0"), (1, "replica 1")}
    # a doc with NO sync pair passes through unshifted (plus rebase)
    bare = {"traceEvents": [{"name": "x", "cat": "c", "ph": "i", "s": "t",
                             "ts": 7.0, "pid": 9, "tid": 0}]}
    merged2 = merge_chrome_traces([bare])
    assert merged2["traceEvents"][0]["ts"] == 0.0
    assert merged2["traceEvents"][0]["pid"] == 9


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_dump_and_flush_order(tmp_path):
    clock = iter(np.arange(1, 500, dtype=float)).__next__
    rec = flightrec.FlightRecorder(str(tmp_path / "box"), capacity=4,
                                   clock=clock, replica=3)
    for i in range(10):
        rec.record("tick", i=i)
    assert len(rec) == 4  # bounded ring
    assert rec.events_total == 10  # lifetime-exact through eviction
    assert [e["i"] for e in rec.events] == [6, 7, 8, 9]
    assert [e["seq"] for e in rec.events] == [6, 7, 8, 9]
    # bind a registry + ledger; register a flush that must run FIRST
    reg = MetricsRegistry()
    reg.counter("/serve/retries").inc(2)
    order = []
    rec.bind(registry=reg, inflight=lambda: order.append("ledger")
             or [{"chunk": 1, "cases": [5]}])
    rec.add_flush(lambda: order.append("flush"))
    path = rec.dump("quarantine", case=5)
    assert order[0] == "flush"  # sinks flushed before the snapshot
    assert os.path.basename(path).startswith("postmortem-")
    assert "-r3-" in path  # replica in the artifact name
    doc = json.load(open(path))
    assert doc["postmortem"] == "quarantine" and doc["case"] == 5
    assert doc["replica"] == 3
    assert [e["i"] for e in doc["events"]] == [6, 7, 8, 9]
    assert doc["registry"]["/serve/retries"] == 2
    assert doc["inflight"] == [{"chunk": 1, "cases": [5]}]
    # a second dump gets its own numbered file (no clobber)
    path2 = rec.dump("sigterm")
    assert path2 != path and os.path.exists(path) and os.path.exists(path2)
    assert rec.dumps == 2


def test_flight_recorder_from_env_and_global_install(tmp_path, capsys):
    assert flightrec.FlightRecorder.from_env({}) is None
    assert flightrec.get_recorder() is None  # suite default
    rec = flightrec.FlightRecorder.from_env(
        {"NLHEAT_FLIGHT_DIR": str(tmp_path / "box")})
    assert rec is not None and os.path.isdir(rec.dir)
    # an unusable dir is loud but not fatal (a FILE in the way)
    blocker = tmp_path / "blocked"
    blocker.write_text("")
    assert flightrec.FlightRecorder.from_env(
        {"NLHEAT_FLIGHT_DIR": str(blocker)}) is None
    assert "flight recorder disabled" in capsys.readouterr().err
    # module-level tap: one attribute read when off, records when on
    flightrec.record("ignored")  # no recorder: dropped silently
    prev = flightrec.set_recorder(rec)
    try:
        flightrec.record("seen", x=1)
    finally:
        flightrec.set_recorder(prev)
    assert [e["kind"] for e in rec.events] == ["seen"]


def test_pipeline_quarantine_triggers_postmortem(tmp_path, monkeypatch):
    # the typed-ServeError trigger: a poison case completing
    # exceptionally dumps the black box, with the event log flushed
    # first and the quarantine event in both artifacts
    log_path = tmp_path / "events.jsonl"
    monkeypatch.setenv("NLHEAT_EVENT_LOG", str(log_path))
    rec = flightrec.FlightRecorder(str(tmp_path / "box"))
    prev = flightrec.set_recorder(rec)
    try:
        engine = EnsembleEngine(batch_sizes=(1,))
        with ServePipeline(engine=engine, depth=1, window_ms=0.0,
                           retries=0, backoff_ms=0.0, fallback=False,
                           sleep=lambda s: None,
                           faults=FaultPlan.parse("nan@c0x*")) as pipe:
            h = pipe.submit(make_cases(1, buckets=1)[0])
            pipe.drain()
    finally:
        flightrec.set_recorder(prev)
    assert h.error is not None
    pms = [f for f in os.listdir(rec.dir) if f.startswith("postmortem-")]
    assert pms, "quarantine did not dump a postmortem"
    doc = json.load(open(os.path.join(rec.dir, sorted(pms)[0])))
    assert doc["postmortem"] == "quarantine"
    assert doc["case"] == 0 and doc["classification"] == "corrupt"
    kinds = [e["kind"] for e in doc["events"]]
    assert "quarantine" in kinds
    assert doc["registry"]["/serve/quarantined"]["count"] == 1
    # the flushed JSONL agrees (never torn: flush ran before the dump)
    lines = read_jsonl(str(log_path))
    assert any(ln["event"] == "quarantine" for ln in lines)


# ---------------------------------------------------------------------------
# event-log seq + merge-sort helper
# ---------------------------------------------------------------------------


def test_merge_event_streams_orders_by_seq_within_process(tmp_path):
    # process A's clock runs 2 ms AHEAD of process B's: naive t-sorting
    # would interleave wrongly WITHIN a process too — seq is
    # authoritative inside, t only merges across
    a = tmp_path / "a.jsonl"
    b = tmp_path / "b.jsonl"
    ta = iter([10.000, 10.001, 10.0005]).__next__  # jittering clock
    la = EventLog(str(a), replica=0, clock=ta)
    for i in range(3):
        la.emit(event="a", i=i)
    la.close()
    lb = EventLog(str(b), replica=1, clock=iter([10.0004, 10.002]).__next__)
    for i in range(2):
        lb.emit(event="b", i=i)
    lb.close()
    merged = merge_event_streams([read_jsonl(str(a)), read_jsonl(str(b))])
    assert len(merged) == 5
    # per-process seq order is strict even where t jitters backwards
    for rep in (0, 1):
        seqs = [e["seq"] for e in merged if e["replica"] == rep]
        assert seqs == sorted(seqs)
    # cross-process: B's first event (t=10.0004) lands before A's second
    kinds = [(e["replica"], e["seq"]) for e in merged]
    assert kinds.index((1, 0)) < kinds.index((0, 1))


# ---------------------------------------------------------------------------
# fleet-scrape staleness + retrace watchdog (in-process pipeline side)
# ---------------------------------------------------------------------------


def test_registry_drop_prefix():
    reg = MetricsRegistry()
    reg.gauge("/replica{3}/serve/depth").set(1)
    reg.counter("/replica{3}/serve/retries").inc()
    reg.gauge("/replica{30}/serve/depth").set(2)  # prefix, not substring
    reg.counter("/router/cases").inc()
    assert reg.drop_prefix("/replica{3}/") == 2
    names = reg.names()
    assert "/replica{30}/serve/depth" in names
    assert "/router/cases" in names
    assert not any(n.startswith("/replica{3}/") for n in names)


def test_steady_state_watchdog_counts_and_warns(capsys, tmp_path,
                                                monkeypatch):
    log_path = tmp_path / "events.jsonl"
    monkeypatch.setenv("NLHEAT_EVENT_LOG", str(log_path))
    engine = EnsembleEngine(batch_sizes=(1,))
    with ServePipeline(engine=engine, depth=1, window_ms=0.0) as pipe:
        pipe.serve_cases(make_cases(2, buckets=1))  # warm-up: 1 bucket
        assert pipe.arm_steady_state() == pipe.report.programs_built
        assert pipe.registry.get("/store/steady-state-builds").value == 0
        pipe.serve_cases(make_cases(2, buckets=1))  # steady: no builds
        assert pipe.registry.get("/store/steady-state-builds").value == 0
        # a NEW bucket after warm-up forces a build: counted + loud
        pipe.serve_cases(make_cases(1, buckets=1, nt=9))
        assert pipe.registry.get("/store/steady-state-builds").value == 1
    err = capsys.readouterr().err
    assert "steady-state recompile" in err
    assert any(ln["event"] == "steady-state-build"
               for ln in read_jsonl(str(log_path)))


# ---------------------------------------------------------------------------
# the golden end-to-end fleet trace (real worker processes)
# ---------------------------------------------------------------------------


def _post_case(base, case):
    body = dict(shape=list(case.shape), nt=case.nt, eps=case.eps, k=case.k,
                dt=case.dt, dh=case.dh,
                u0=np.asarray(case.u0).tolist())
    r = urllib.request.urlopen(urllib.request.Request(
        base + "/v1/cases", json.dumps(body).encode()))
    assert r.status == 202
    return json.load(r), r.headers.get("X-NLHEAT-Trace")


def _run_chaos_fleet(tmp_path, replicas, cases, die_plan):
    """One traced + black-boxed routed run through the HTTP ingress,
    with a worker-side injected retry and a router-side die@ kill.
    Returns (merged_doc, postmortem_doc, ingress_trace_ids, results,
    stale_names_before_prune, router_registry_names_after_prune)."""
    trace_dir = str(tmp_path / "trace")
    flight_dir = str(tmp_path / "flight")
    with ReplicaRouter(
            replicas=replicas, method="sat", batch_sizes=(1,),
            trace_dir=trace_dir, flight_dir=flight_dir,
            faults=die_plan, respawn=True,
            # one injected retry: every worker's FIRST dispatch attempt
            # raises and is retried (the pipeline's own supervision)
            serve_kwargs={"faults": FaultPlan.parse("raise@0"),
                          "backoff_ms": 0.0}) as router:
        ing = IngressServer(0, router)
        try:
            base = f"http://127.0.0.1:{ing.port}"
            ids, traces = [], []

            def post(sub):
                for c in sub:
                    d, hdr = _post_case(base, c)
                    ids.append(d["id"])
                    traces.append(d["trace"])
                    assert hdr.startswith(d["trace"])

            # warm phase BEFORE the die@ plan fires: serve a couple of
            # cases and absorb every replica's stats, so the doomed
            # replica has a /replica{r} namespace to go stale when the
            # kill lands mid-run below
            post(cases[:2])
            for i in ids:
                urllib.request.urlopen(
                    base + f"/v1/cases/{i}?wait=1&timeout_s=300")
            router.refresh_stats()
            post(cases[2:])
            results = []
            for i in ids:
                r = urllib.request.urlopen(
                    base + f"/v1/cases/{i}?wait=1&timeout_s=300")
                d = json.load(r)
                assert d["status"] == "done", d
                r = urllib.request.urlopen(
                    base + f"/v1/cases/{i}/result")
                res = json.load(r)
                results.append(
                    np.asarray(res["values"]).reshape(res["shape"]))
            # staleness: absorb live stats, then label/drop the dead
            # replica's namespace (death already happened above)
            router.refresh_stats()
            names_in_window = router.registry.names()
            router.stale_after_s = 0.0  # window elapsed
            router.refresh_stats()
            names_after = router.registry.names()
            # the merged artifact must carry every live worker's pid,
            # but a worker respawned after the die@ kill only traces
            # once it SERVES — and the survivors can drain the batch
            # before the fresh spawn (a jax import) wins a case.  Top
            # the fleet up (bounded) until every worker has traced:
            # _pick_replica prefers the zero-bucket fresh worker, so
            # one routed case per round converges.  A chaos-timing
            # guard, not a behavior pin — the top-up trace ids are
            # ingress-minted like any other (recorded in ``traces``).
            tpath = os.path.join(trace_dir, "fleet_trace.json")
            for i in range(8):
                merged = router.dump_fleet_trace(tpath)
                assert merged is not None and merged["processes"] >= 2
                wpids = {e["pid"]
                         for e in json.load(open(tpath))["traceEvents"]
                         if e["ph"] != "M"}
                if len(wpids) > replicas:  # router pid + all workers
                    break
                time.sleep(0.25)  # let an in-flight respawn get ready
                # a FRESH bucket per round: a warm bucket routes sticky
                # to its owner, never to the zero-bucket fresh worker
                d, _hdr = _post_case(
                    base, make_cases(1, grid=8, nt=20 + i, buckets=1,
                                     seed=99 + i)[0])
                traces.append(d["trace"])
                urllib.request.urlopen(
                    base + f"/v1/cases/{d['id']}?wait=1&timeout_s=300")
        finally:
            ing.close()
    # surviving workers wrote per-replica artifacts at clean stop
    # (NLHEAT_REPLICA_ID in the path); the killed one's ring died with
    # it BY DESIGN — its story is the postmortem's job
    per_replica = [f for f in os.listdir(trace_dir)
                   if f.startswith("host_trace.replica")]
    assert per_replica, "no per-replica trace artifact written"
    one = json.load(open(os.path.join(trace_dir, per_replica[0])))
    assert one["metadata"]["replica"] is not None
    assert "clock_sync" in one["metadata"]
    doc = json.load(open(os.path.join(trace_dir, "fleet_trace.json")))
    pms = sorted(f for f in os.listdir(flight_dir)
                 if f.startswith("postmortem-"))
    assert pms, "the die@ kill left no postmortem"
    pm = json.load(open(os.path.join(flight_dir, pms[0])))
    return doc, pm, traces, results, names_in_window, names_after


def test_golden_end_to_end_fleet_trace_with_retry_and_die(tmp_path):
    cases = make_cases(6, buckets=2)
    offline = EnsembleEngine(method="sat", batch_sizes=(1,)).run(cases)
    doc, pm, traces, results, stale_names, pruned_names = \
        _run_chaos_fleet(tmp_path, 2, cases, "die@2")
    # served results bit-identical to offline, tracing + chaos on
    for got, want in zip(results, offline, strict=True):
        assert np.array_equal(got, want)

    # -- the merged artifact is schema-valid and multi-process ----------
    events = doc["traceEvents"]
    _check_schema(events)
    pids = {e["pid"] for e in events if e["ph"] != "M"}
    assert len(pids) >= 3  # ingress/router pid + >= 2 replica pids
    labels = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert "router" in labels
    assert any(lbl.startswith("replica") for lbl in labels)

    # -- every stamped span chains to an ingress-minted request ---------
    minted = set(traces)
    # one identity per request (>= : the helper's trace-coverage top-up
    # may mint a few beyond the offline-compared batch)
    assert len(minted) == len(traces) >= len(cases)
    stamped = [e for e in events
               if e.get("args", {}).get("trace") is not None]
    assert stamped, "no span carries a trace id"
    assert {e["args"]["trace"] for e in stamped} <= minted
    # worker-side chunk spans (pid = replica) carry the stamp too: the
    # re-install ACROSS the pickle frame boundary is what is being pinned
    worker_stamped = [e for e in stamped
                     if e["name"].startswith("serve.")
                     and e["pid"] != max(pids)]
    assert worker_stamped, "no worker-side span chains to its request"

    # -- the injected retry is visible --------------------------------
    retries = [e for e in events if e["name"] == "serve.retry"]
    assert retries, "the injected raise@0 retry left no span"

    # -- flow events connect across pids -------------------------------
    flows: dict = {}
    for e in events:
        if e["ph"] in ("s", "t", "f"):
            flows.setdefault(e["id"], []).append(e)
    assert set(flows) <= minted
    crossing = [fid for fid, evs in flows.items()
                if {x["ph"] for x in evs} >= {"s", "t", "f"}
                and len({x["pid"] for x in evs}) >= 2]
    assert crossing, "no request flow crosses a process boundary"
    for fid in crossing:
        evs = sorted(flows[fid], key=lambda x: x["ts"])
        phases = [x["ph"] for x in evs]
        assert phases[0] == "s"  # rooted at the ingress
        assert phases[-1] == "f"  # finished at a worker retire

    # -- the postmortem names the killed replica + orphans + decisions --
    assert pm["postmortem"] == "replica-death"
    dead = pm["replica"]
    assert isinstance(dead, int)
    assert pm["orphans"], "no orphaned cases recorded"
    acts = {d["action"] for d in pm["decisions"]}
    assert acts <= {"re-route", "quarantine", "failed"}
    assert {d["case"] for d in pm["decisions"]} == set(pm["orphans"])
    assert any(d["action"] == "re-route" for d in pm["decisions"])
    kinds = [e["kind"] for e in pm["events"]]
    assert "replica-death" in kinds and "re-route" in kinds
    assert "inflight" in pm and "registry" in pm

    # -- staleness: labeled in the window, dropped after ----------------
    stale_flag = f"/replica{{{dead}}}/stale"
    assert stale_flag in stale_names  # labeled while inside the window
    assert any(n.startswith(f"/replica{{{dead}}}/serve")
               for n in stale_names)  # gauges still present (labeled)
    assert not any(n.startswith(f"/replica{{{dead}}}/")
                   for n in pruned_names)  # dropped past the window


@pytest.mark.slow  # the ISSUE 11 acceptance shape verbatim: a 4-replica
# chaos fleet is ~5 worker spawns (jax import each); the 2-replica
# golden test above pins the same machinery inside the tier-1 budget
def test_acceptance_four_replica_chaos_run(tmp_path):
    cases = make_cases(12, buckets=4)
    offline = EnsembleEngine(method="sat", batch_sizes=(1,)).run(cases)
    doc, pm, traces, results, _stale, _pruned = \
        _run_chaos_fleet(tmp_path, 4, cases, "die@3")
    for got, want in zip(results, offline, strict=True):
        assert np.array_equal(got, want)
    events = doc["traceEvents"]
    _check_schema(events)
    pids = {e["pid"] for e in events if e["ph"] != "M"}
    assert len(pids) >= 4  # router + surviving/respawned replicas
    flows: dict = {}
    for e in events:
        if e["ph"] in ("s", "t", "f"):
            flows.setdefault(e["id"], []).append(e)
    assert any({x["ph"] for x in evs} >= {"s", "t", "f"}
               and len({x["pid"] for x in evs}) >= 2
               for evs in flows.values())
    assert pm["postmortem"] == "replica-death" and pm["orphans"]
    assert any(d["action"] == "re-route" for d in pm["decisions"])
