"""jax.profiler trace capture (utils/profiling.py) — SURVEY.md section 5's
TPU tracing equivalent.  Verifies a trace is actually written around a solve
and that profiling never breaks the solve itself."""

import os

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.utils.profiling import trace


def test_trace_captures_solve(tmp_path):
    logdir = str(tmp_path / "trace")
    s = Solver2D(20, 20, 3, eps=3, k=1.0, dt=1e-4, dh=0.05, backend="jit")
    s.test_init()
    with trace(logdir):
        s.do_work()
    assert s.error_l2 / 400 <= 1e-6
    # jax writes plugins/profile/<ts>/... under the log dir
    found = [os.path.join(r, f) for r, _, fs in os.walk(logdir) for f in fs]
    assert found, "no trace files written"


def test_trace_none_is_noop():
    s = Solver2D(10, 10, 2, eps=2, backend="jit")
    s.test_init()
    with trace(None):
        s.do_work()
    assert s.u is not None
