"""jax.profiler trace capture (utils/profiling.py) — SURVEY.md section 5's
TPU tracing equivalent.  Verifies a trace is actually written around a solve,
that profiling never breaks the solve itself, and (the ISSUE 5 bugfix) that
``--profile`` now wraps the batch drivers — served and ensemble workloads
included — instead of only the solo-solve path."""

import os

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.utils.profiling import trace


def test_trace_captures_solve(tmp_path):
    logdir = str(tmp_path / "trace")
    s = Solver2D(20, 20, 3, eps=3, k=1.0, dt=1e-4, dh=0.05, backend="jit")
    s.test_init()
    with trace(logdir):
        s.do_work()
    assert s.error_l2 / 400 <= 1e-6
    # jax writes plugins/profile/<ts>/... under the log dir
    found = [os.path.join(r, f) for r, _, fs in os.walk(logdir) for f in fs]
    assert found, "no trace files written"


def test_trace_none_is_noop():
    s = Solver2D(10, 10, 2, eps=2, backend="jit")
    s.test_init()
    with trace(None):
        s.do_work()
    assert s.u is not None


def test_run_batch_threads_profile_to_every_mode(monkeypatch, capsys):
    """The ISSUE 5 bugfix, unit level: ``run_batch(profile=...)`` wraps
    the sequential, ensemble, AND served drivers in one profiling
    context — and ``profile=None`` stays the no-op path (``trace(None)``
    yields immediately; the drivers run outside any capture)."""
    from nonlocalheatequation_tpu.cli import common
    from nonlocalheatequation_tpu.utils import profiling

    captures = []

    import contextlib

    @contextlib.contextmanager
    def spy_trace(log_dir):
        captures.append(("enter", log_dir))
        yield
        captures.append(("exit", log_dir))

    monkeypatch.setattr(profiling, "trace", spy_trace)
    monkeypatch.setattr("sys.stdin", __import__("io").StringIO("1\n7\n"))

    def read_case(toks, pos):
        return ((int(toks[pos]),), pos + 1)

    def run_serve(case_iter):
        assert captures == [("enter", "DIR")]  # serving runs INSIDE
        return [(0.0, n) for (n,) in case_iter]

    rc = common.run_batch(read_case, None, row_tokens=1,
                          run_serve=run_serve, profile="DIR")
    assert rc == 0 and captures == [("enter", "DIR"), ("exit", "DIR")]
    assert "Tests Passed" in capsys.readouterr().out

    captures.clear()
    monkeypatch.setattr("sys.stdin", __import__("io").StringIO("1\n7\n"))

    def run_ensemble(cases):
        assert captures == [("enter", None)]
        return [(0.0, n) for (n,) in cases]

    # profile=None: the no-op path — trace(None) is entered (and is a
    # no-op, test_trace_none_is_noop) so the disabled wiring adds nothing
    rc = common.run_batch(read_case, None, row_tokens=1,
                          run_ensemble=run_ensemble, profile=None)
    assert rc == 0 and captures == [("enter", None), ("exit", None)]
    capsys.readouterr()


def test_profile_flag_captures_served_batch(tmp_path):
    """The bugfix, end to end: a ``--serve`` batch under ``--profile``
    writes a real jax.profiler capture around the pipelined workload."""
    import subprocess
    import sys

    logdir = str(tmp_path / "prof")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "-m", "nonlocalheatequation_tpu.cli.solve2d",
         "--platform", "cpu", "--test_batch", "--serve", "2",
         "--profile", logdir],
        input="2\n32 32 10 5 1 0.001 0.03125\n32 32 10 5 1 0.001 0.03125\n",
        capture_output=True, text=True, timeout=540, cwd=repo)
    assert "Tests Passed" in r.stdout, r.stdout + r.stderr
    found = [os.path.join(rt, f) for rt, _, fs in os.walk(logdir) for f in fs]
    assert found, "no profiler capture written around the served batch"
