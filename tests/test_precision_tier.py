"""bf16 precision tier: semantics, error budget, variant equality, knobs.

The tier's contract (ops/constants.py) is deliberately different from the
f32 fast paths': operators read the bfloat16 ROUNDING of the state
(accumulated at full precision, f32 master carry), so 1e-12 oracle parity
is unreachable by construction and the tier instead pins

* exact semantics: every method computes sum over round_bf16(u) (the
  shift path is the reference; sat/conv/pallas agree up to addition
  order), with the Wsum*u center term rounded identically so
  L(const) == 0 survives;
* a measured manufactured-solution budget at a STABLE dt
  (constants.BF16_L2_BUDGET — see the stability caveat there);
* bit-identity among the tier's multi-step variants (per-step pad path
  vs carried pair-frame vs K-step superstep);
* loud refusal from variants with no bf16 implementation (resident,
  carried3d);
* the f32 default staying byte-for-byte the pre-tier program (the
  `_operand` transform is the identity, pinned here; the deep parity
  evidence is the untouched 1e-12 suite).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nonlocalheatequation_tpu.ops.constants import BF16_L2_BUDGET
from nonlocalheatequation_tpu.ops.nonlocal_op import (
    NonlocalOp2D,
    NonlocalOp3D,
    make_multi_step_fn,
    make_multi_step_fn_base,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _stable_op(n, eps, method="sat", **kw):
    probe = NonlocalOp2D(eps, 1.0, 1.0, 1.0 / n, method=method)
    dt = 0.8 / (probe.c * probe.dh**2 * probe.wsum)
    return NonlocalOp2D(eps, 1.0, dt, 1.0 / n, method=method, **kw)


def test_default_tier_is_f32_and_validated():
    op = NonlocalOp2D(3, 1.0, 1e-4, 0.01)
    assert op.precision == "f32" and op.resync_every == 0
    u = jnp.ones((4, 4))
    assert op._operand(u) is u  # the f32 transform is the identity
    with pytest.raises(ValueError, match="unknown precision tier"):
        NonlocalOp2D(3, 1.0, 1e-4, 0.01, precision="fp8")
    with pytest.raises(ValueError, match="bf16-tier knob"):
        NonlocalOp2D(3, 1.0, 1e-4, 0.01, resync_every=4)
    with pytest.raises(ValueError, match="resync_every"):
        NonlocalOp2D(3, 1.0, 1e-4, 0.01, precision="bf16", resync_every=-1)


def test_bf16_semantics_is_round_then_full_precision_sum():
    # the tier == the f32 operator applied to the bf16-rounded state,
    # EXACTLY (same method, same addition order)
    rng = np.random.default_rng(0)
    u = jnp.asarray(rng.normal(size=(50, 37)))
    op_b = NonlocalOp2D(5, 1.0, 1e-4, 0.01, method="shift", precision="bf16")
    op_f = NonlocalOp2D(5, 1.0, 1e-4, 0.01, method="shift")
    ur = u.astype(jnp.bfloat16).astype(u.dtype)
    assert np.array_equal(np.asarray(op_b.neighbor_sum(u)),
                          np.asarray(op_f.neighbor_sum(ur)))
    assert np.array_equal(np.asarray(op_b.apply(u)),
                          np.asarray(op_f.apply(ur)))


@pytest.mark.parametrize("method", ["sat", "conv", "pallas"])
def test_bf16_methods_agree_with_shift(method):
    rng = np.random.default_rng(1)
    u = jnp.asarray(rng.normal(size=(40, 33)))
    ref = NonlocalOp2D(4, 1.0, 1e-4, 0.01, method="shift",
                       precision="bf16").neighbor_sum(u)
    got = NonlocalOp2D(4, 1.0, 1e-4, 0.01, method=method,
                       precision="bf16").neighbor_sum(u)
    assert float(jnp.max(jnp.abs(ref - got))) < 1e-10


def test_bf16_conv_mixed_precision_branch_f32():
    """The genuinely-mixed conv path (bf16 operand x bf16 0/1 mask with
    preferred_element_type=f32) only engages for f32 state + uniform J;
    the f64 suite otherwise never executes it.  Pin it against the shift
    reference on f32 inputs, and pin that a weighted J (bf16-inexact
    weights possible) routes through the full-precision-kernel branch."""
    rng = np.random.default_rng(9)
    u32 = jnp.asarray(rng.normal(size=(40, 33)), jnp.float32)
    op_c = NonlocalOp2D(4, 1.0, 1e-4, 0.01, method="conv", precision="bf16")
    assert op_c.uniform
    ref = NonlocalOp2D(4, 1.0, 1e-4, 0.01, method="shift",
                       precision="bf16").neighbor_sum(u32)
    got = op_c.neighbor_sum(u32)
    assert got.dtype == jnp.float32
    # f32 accumulation of identical bf16 operands, different add order
    assert float(jnp.max(jnp.abs(ref - got))) < 1e-4
    # weighted J: weights stay full precision (only the STATE rounds)
    infl = lambda r: 1.0 / (1.0 + 3.1 * r)  # noqa: E731
    op_w = NonlocalOp2D(4, 1.0, 1e-4, 0.01, influence=infl, method="conv",
                        precision="bf16")
    ref_w = NonlocalOp2D(4, 1.0, 1e-4, 0.01, influence=infl,
                         method="shift", precision="bf16").neighbor_sum(u32)
    assert float(jnp.max(jnp.abs(ref_w - op_w.neighbor_sum(u32)))) < 1e-4


def test_bf16_3d_methods_agree():
    rng = np.random.default_rng(2)
    u = jnp.asarray(rng.normal(size=(20, 20, 20)))
    ref = NonlocalOp3D(3, 1.0, 1e-7, 0.05, method="shift",
                       precision="bf16").neighbor_sum(u)
    for method in ("sat", "pallas"):
        got = NonlocalOp3D(3, 1.0, 1e-7, 0.05, method=method,
                           precision="bf16").neighbor_sum(u)
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-10, method


def test_manufactured_accuracy_budget_bf16():
    """The tier's headline contract: measured error_l2/#points vs the f64
    manufactured solution, at a STABLE dt, within the documented budget —
    and strictly worse than f32 (a budget nothing ever approaches would
    be a fake gate)."""
    from nonlocalheatequation_tpu.models.solver2d import Solver2D

    for n, eps, nt in [(48, 4, 40), (50, 5, 45)]:
        probe = NonlocalOp2D(eps, 1.0, 1.0, 1.0 / n)
        dt = 0.8 / (probe.c * probe.dh**2 * probe.wsum)
        errs = {}
        for prec in ("f32", "bf16"):
            s = Solver2D(n, n, nt, eps, k=1.0, dt=dt, dh=1.0 / n,
                         backend="jit", method="sat", precision=prec,
                         dtype=jnp.float64)
            s.test_init()
            s.do_work()
            errs[prec] = s.error_l2 / (n * n)
        assert errs["bf16"] <= BF16_L2_BUDGET, (n, eps, errs)
        assert errs["f32"] <= 1e-6, (n, eps, errs)
        # the tier's rounding must be VISIBLE (orders of magnitude above
        # f32) or the budget is testing nothing
        assert errs["bf16"] > 100 * errs["f32"], (n, eps, errs)


def test_carried_bf16_bit_identical_to_per_step():
    from nonlocalheatequation_tpu.ops.pallas_kernel import (
        make_carried_multi_step_fn,
    )

    rng = np.random.default_rng(3)
    for n, eps, steps in [(64, 5, 4), (40, 3, 3), (48, 12, 2)]:
        op = NonlocalOp2D(eps, k=1.0, dt=1e-6, dh=1.0 / n, method="pallas",
                          precision="bf16")
        ref = make_multi_step_fn_base(op, steps, dtype=jnp.float32)
        new = make_carried_multi_step_fn(op, steps, dtype=jnp.float32)
        u = jnp.asarray(rng.normal(size=(n, n)), jnp.float32)
        a = np.asarray(ref(u, jnp.int32(0)))
        b = np.asarray(new(u, jnp.int32(0)))
        assert np.array_equal(a, b), (n, eps, np.abs(a - b).max())


def test_superstep_bf16_bit_identical_to_per_step():
    from nonlocalheatequation_tpu.ops.pallas_kernel import (
        make_superstep_multi_step_fn,
    )

    rng = np.random.default_rng(4)
    # remainders, K > 2, ragged grid, smoothed state (the historical
    # fusion-boundary ulp-flip case) — mirroring the f32 superstep suite
    for n, eps, steps, K in [(64, 5, 5, 2), (40, 3, 6, 3), (33, 4, 4, 2),
                             (48, 12, 2, 2)]:
        op = NonlocalOp2D(eps, k=1.0, dt=1e-6, dh=1.0 / n, method="pallas",
                          precision="bf16")
        ref = make_multi_step_fn_base(op, steps, dtype=jnp.float32)
        new = make_superstep_multi_step_fn(op, steps, ksteps=K,
                                           dtype=jnp.float32)
        u = jnp.asarray(rng.normal(size=(n, n)), jnp.float32)
        v = ref(u, jnp.int32(0))
        for w in (u, v):
            a = np.asarray(ref(w, jnp.int32(0)))
            b = np.asarray(new(w, jnp.int32(0)))
            assert np.array_equal(a, b), (n, eps, steps, K,
                                          np.abs(a - b).max())


def test_variants_without_bf16_tier_refuse_loudly():
    from nonlocalheatequation_tpu.ops.pallas_kernel import (
        make_carried_multi_step_fn_3d,
        make_resident_multi_step_fn,
        make_resident_multi_step_fn_3d,
    )

    op2 = NonlocalOp2D(4, k=1.0, dt=1e-6, dh=0.02, method="pallas",
                       precision="bf16")
    op3 = NonlocalOp3D(3, k=1.0, dt=1e-7, dh=0.05, method="pallas",
                       precision="bf16")
    with pytest.raises(ValueError, match="no bf16 precision tier"):
        make_resident_multi_step_fn(op2, 2)
    with pytest.raises(ValueError, match="no bf16 precision tier"):
        make_resident_multi_step_fn_3d(op3, 2)
    with pytest.raises(ValueError, match="no bf16 precision tier"):
        make_carried_multi_step_fn_3d(op3, 2)


def test_resync_every_1_equals_f32_path():
    rng = np.random.default_rng(5)
    u = jnp.asarray(rng.normal(size=(48, 48)), jnp.float32)
    op_r = _stable_op(48, 4, precision="bf16", resync_every=1)
    op_f = _stable_op(48, 4)
    a = np.asarray(make_multi_step_fn(op_r, 5, dtype=jnp.float32)(
        u, jnp.int32(0)))
    b = np.asarray(make_multi_step_fn(op_f, 5, dtype=jnp.float32)(
        u, jnp.int32(0)))
    assert np.array_equal(a, b)


def test_resync_schedule_matches_manual_alternation():
    """resync_every=R runs the f32 step exactly when (t+1) % R == 0
    (absolute timestep index), the bf16 step otherwise.  The compiled
    lax.cond scan may differ from a host-side step loop by last ulps
    (XLA fusion context — the same effect the superstep kernel pins with
    an optimization_barrier), so the schedule is asserted to ulp-level
    tolerance plus distinctness from BOTH pure tiers: bf16 rounding
    injects ~2^-9 perturbations, orders of magnitude above ulp noise,
    so a mis-scheduled step count cannot hide inside the tolerance."""
    from nonlocalheatequation_tpu.ops.nonlocal_op import make_step_fn

    rng = np.random.default_rng(6)
    u0 = jnp.asarray(rng.normal(size=(32, 32)), jnp.float32)
    op_b = _stable_op(32, 3, precision="bf16", resync_every=3)
    step_lo = make_step_fn(op_b, dtype=jnp.float32)
    step_hi = make_step_fn(op_b.with_precision("f32"), dtype=jnp.float32)
    want = u0
    for t in range(7):
        want = (step_hi if (t + 1) % 3 == 0 else step_lo)(want, jnp.int32(t))
    got = np.asarray(
        make_multi_step_fn(op_b, 7, dtype=jnp.float32)(u0, jnp.int32(0)))
    # measured separation at this config: want-got ~3.6e-7 (fusion noise)
    # vs ~2-4e-4 to either pure tier (the schedule's real signal)
    scale = np.abs(np.asarray(want)).max()
    assert np.abs(np.asarray(want) - got).max() < 1e-5 * scale
    pure_lo = np.asarray(make_multi_step_fn(
        op_b.with_precision("bf16"), 7, dtype=jnp.float32)(u0, jnp.int32(0)))
    pure_hi = np.asarray(make_multi_step_fn(
        op_b.with_precision("f32"), 7, dtype=jnp.float32)(u0, jnp.int32(0)))
    assert np.abs(got - pure_lo).max() > 1e-4 * scale  # resync engaged
    assert np.abs(got - pure_hi).max() > 1e-4 * scale  # still the bf16 tier


def test_bf16_resync_improves_manufactured_error():
    from nonlocalheatequation_tpu.models.solver2d import Solver2D

    n, eps, nt = 48, 4, 40
    probe = NonlocalOp2D(eps, 1.0, 1.0, 1.0 / n)
    dt = 0.8 / (probe.c * probe.dh**2 * probe.wsum)
    errs = {}
    for r in (0, 2):
        s = Solver2D(n, n, nt, eps, k=1.0, dt=dt, dh=1.0 / n, backend="jit",
                     method="sat", precision="bf16", resync_every=r,
                     dtype=jnp.float64)
        s.test_init()
        s.do_work()
        errs[r] = s.error_l2 / (n * n)
    # replacing half the rounded-operand steps with full-precision steps
    # must cut the error materially (it roughly halves the injected noise)
    assert errs[2] < 0.8 * errs[0], errs


def test_distributed_bf16_matches_serial_bf16():
    from nonlocalheatequation_tpu.models.solver2d import Solver2D
    from nonlocalheatequation_tpu.parallel.distributed2d import (
        Solver2DDistributed,
    )
    from nonlocalheatequation_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2, 4)
    a = Solver2DDistributed(16, 8, 2, 4, nt=3, eps=2, k=1.0, dt=1e-4,
                            dh=0.03125, mesh=mesh, method="shift",
                            precision="bf16")
    a.test_init()
    a.do_work()
    b = Solver2D(32, 32, 3, eps=2, k=1.0, dt=1e-4, dh=0.03125,
                 backend="jit", method="shift", precision="bf16")
    b.test_init()
    b.do_work()
    assert np.abs(a.u - b.u).max() < 1e-12
    with pytest.raises(ValueError, match="resync_every is not supported"):
        Solver2DDistributed(16, 8, 2, 4, nt=3, eps=2, k=1.0, dt=1e-4,
                            dh=0.03125, mesh=mesh, precision="bf16",
                            resync_every=2)


def test_autotune_precision_dimension_and_gate(monkeypatch, tmp_path):
    from nonlocalheatequation_tpu.utils import autotune

    monkeypatch.setenv("NLHEAT_AUTOTUNE_CACHE", "")
    monkeypatch.setenv("NLHEAT_TUNE_PRECISION", "1")
    op = NonlocalOp2D(3, k=1.0, dt=1e-6, dh=1.0 / 48, method="pallas")

    # force the bf16 per-step candidate to "win" the timing probe
    real_measure = autotune._measure

    def biased(maker, op_, shape, dtype):
        del maker, op_, shape, dtype
        return 1.0

    names_seen = []
    monkeypatch.setattr(
        autotune, "_measure",
        lambda maker, op_, shape, dtype: names_seen.append(1) or 1.0)
    # deterministic gate result without the probe cost
    monkeypatch.setattr(
        autotune, "_bf16_gate",
        lambda *a, **kw: {"l2_per_n": 0.0, "budget": 1.0, "ok": True})
    autotune._memory_cache.clear()
    fn, winner = autotune.pick_multi_step_fn(op, 6, (48, 48), jnp.float32)
    entry = next(iter(autotune._memory_cache.values()))
    probed = set(entry["ms_per_step"])
    assert any(n.endswith("+bf16") for n in probed), probed
    assert "resident+bf16" not in probed  # no bf16 resident candidate
    assert entry["bf16_gate"]["ok"] is True

    # gate failure: identical timings, but the tier is ineligible — an
    # f32 candidate must win even though bf16 ties on speed
    autotune._memory_cache.clear()
    monkeypatch.setattr(
        autotune, "_measure",
        lambda maker, op_, shape, dtype: 0.001
        if True else real_measure(maker, op_, shape, dtype))
    monkeypatch.setattr(
        autotune, "_bf16_gate",
        lambda *a, **kw: {"l2_per_n": 1.0, "budget": 1e-5, "ok": False})
    fn, winner = autotune.pick_multi_step_fn(op, 6, (48, 48), jnp.float32)
    assert not winner.endswith("+bf16"), winner
    entry = next(iter(autotune._memory_cache.values()))
    assert entry["bf16_gate"]["ok"] is False

    # the built winner still runs and matches the pinned per-step path
    u = jnp.asarray(np.random.default_rng(7).normal(size=(48, 48)),
                    jnp.float32)
    ref = make_multi_step_fn_base(op, 6, dtype=jnp.float32)(u, jnp.int32(0))
    assert np.array_equal(np.asarray(ref), np.asarray(fn(u, jnp.int32(0))))


def test_bf16_op_candidates_exclude_unimplemented_variants():
    from nonlocalheatequation_tpu.utils.autotune import candidates

    op2 = NonlocalOp2D(3, k=1.0, dt=1e-6, dh=1.0 / 48, method="pallas",
                       precision="bf16")
    names2 = {n for n, _ in candidates(op2, (48, 48), 6, jnp.float32)}
    assert "resident" not in names2
    assert {"per-step", "carried"} <= names2
    op3 = NonlocalOp3D(3, k=1.0, dt=1e-7, dh=1.0 / 24, method="pallas",
                       precision="bf16")
    names3 = {n for n, _ in candidates(op3, (24, 24, 24), 4, jnp.float32)}
    assert names3 == {"per-step"}


def test_donation_results_unchanged(monkeypatch):
    """NLHEAT_DONATE=1 (forced donation, CPU included — this jaxlib
    enforces CPU donation) must not change results; fresh arrays per
    call because donated inputs are consumed."""
    from nonlocalheatequation_tpu.ops.pallas_kernel import (
        make_carried_multi_step_fn,
    )

    op = NonlocalOp2D(4, k=1.0, dt=1e-6, dh=1.0 / 40, method="pallas")
    host = np.random.default_rng(8).normal(size=(40, 40)).astype(np.float32)

    def run(maker):
        return np.asarray(maker(op, 3, dtype=jnp.float32)(
            jnp.asarray(host), jnp.int32(0)))

    monkeypatch.setenv("NLHEAT_DONATE", "0")
    base_off = run(make_multi_step_fn_base)
    carried_off = run(make_carried_multi_step_fn)
    monkeypatch.setenv("NLHEAT_DONATE", "1")
    base_on = run(make_multi_step_fn_base)
    carried_on = run(make_carried_multi_step_fn)
    assert np.array_equal(base_off, base_on)
    assert np.array_equal(carried_off, carried_on)


def _run_bench(env, tmp_path):
    full = dict(os.environ)
    for k in list(full):
        if k.startswith(("BENCH_", "NLHEAT_")):
            full.pop(k)
    full.update(
        BENCH_PLATFORM="cpu",
        BENCH_GRID="48",
        BENCH_LADDER="48",
        BENCH_EPS="3",
        BENCH_STEPS="2",
        BENCH_ACCURACY="0",
        BENCH_WATCHDOG_S="240",
        BENCH_PROBE_PHASE_S="60",
        BENCH_COMPILE_CACHE_DIR=str(tmp_path / "xla_cache"),
        **env,
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=300, env=full, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, proc.stdout  # the one-JSON-line contract
    return json.loads(lines[0]), proc.stderr


def test_bench_precision_field_and_compile_cache_cold_start(tmp_path):
    rec, err = _run_bench({"BENCH_PRECISION": "bf16"}, tmp_path)
    assert rec["precision"] == "bf16"
    assert "compile_s" in rec
    assert "cold start" in err
    cache = tmp_path / "xla_cache"
    assert cache.is_dir() and len(list(cache.iterdir())) > 0


@pytest.mark.slow  # a second full bench subprocess (~20 s); the cold half
# above already pins the cache populating and the cold/warm log line
def test_bench_compile_cache_warm_start(tmp_path):
    rec, err = _run_bench({}, tmp_path)
    assert "cold start" in err
    rec2, err2 = _run_bench({}, tmp_path)
    assert rec2["precision"] == "f32"  # the default, and always present
    assert "warm start" in err2
    # same shapes, persistent cache: the warm compile+first-run time must
    # not exceed the cold one by more than jitter (on TPU the win is the
    # whole ~7 s XLA compile; on CPU it is small but never negative-large)
    assert rec2["compile_s"] <= rec["compile_s"] * 2 + 1.0


def test_cli_precision_flags_parse_and_wire():
    from nonlocalheatequation_tpu.cli.common import precision_kwargs
    from nonlocalheatequation_tpu.cli.solve2d import build_parser

    args = build_parser().parse_args(
        ["--test", "--precision", "bf16", "--resync", "4"])
    assert precision_kwargs(args) == {"precision": "bf16",
                                      "resync_every": 4}
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--precision", "fp8"])
