"""tools/gen_docs.py --check must actually FAIL on a stale page.

The tier-1 flow trusts --check to guard the generated docs/api tree, but
a checker is only as good as its last proven failure (ISSUE 2 satellite):
these tests build the pages into a scratch tree (GEN_DOCS_OUT) and
assert rc=1 for a corrupted page, a deleted page, and an orphan page —
and rc=0 again after a regen.  Runs in-process (the module is importable
and OUT is env-overridable) so the suite pays no extra interpreter
startups.
"""

import importlib
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _gen_docs(tmp_path, monkeypatch, *argv):
    monkeypatch.setenv("GEN_DOCS_OUT", str(tmp_path))
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import gen_docs

        gen_docs = importlib.reload(gen_docs)  # re-read GEN_DOCS_OUT
    finally:
        sys.path.pop(0)
    monkeypatch.setattr(sys, "argv", ["gen_docs.py", *argv])
    return gen_docs.main()


def test_check_fails_on_stale_deleted_and_orphan_pages(
        tmp_path, monkeypatch, capsys):
    out = tmp_path / "api"
    assert _gen_docs(out, monkeypatch) == 0  # fresh build
    assert _gen_docs(out, monkeypatch, "--check") == 0  # clean tree passes
    pages = sorted(p for p in out.iterdir() if p.suffix == ".md")
    assert len(pages) > 10  # the whole package rendered

    # stale: corrupt one page
    victim = next(p for p in pages if "ensemble" in p.name)
    victim.write_text("# stale\n")
    assert _gen_docs(out, monkeypatch, "--check") == 1
    assert victim.name in capsys.readouterr().out

    # regen heals it
    assert _gen_docs(out, monkeypatch) == 0
    assert _gen_docs(out, monkeypatch, "--check") == 0

    # deleted page
    victim.unlink()
    assert _gen_docs(out, monkeypatch, "--check") == 1

    _gen_docs(out, monkeypatch)
    # orphan page (a module that no longer exists)
    (out / "nonlocalheatequation_tpu_gone.md").write_text("# orphan\n")
    assert _gen_docs(out, monkeypatch, "--check") == 1
