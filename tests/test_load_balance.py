"""Load-balancer tests — the C4e analog (SURVEY.md sections 3.5, 5).

Covers: the work_realloc formula + dead-band (reference
src/2d_nonlocal_distributed.cpp:906-919), region rebalancing from the
reference's deliberately imbalanced fixture layouts (tests/load_balance_*.txt
shapes: 24-of-25 tiles on one node), the <=1500/10000 acceptance criterion
(:682-685), elastic-solver correctness under arbitrary placement and under
live migration, placement-independence (determinism), and heterogeneous
device speeds.
"""

import numpy as np
import pytest

import jax

from nonlocalheatequation_tpu.parallel import load_balance as lb
from nonlocalheatequation_tpu.parallel.elastic import ElasticSolver2D
from nonlocalheatequation_tpu.utils.partition_map import default_assignment


def imbalanced_map(npx=5, npy=5, heavy_owner=1, light_owner=0):
    """The reference's load_balance_25s_2n.txt shape: 24 of 25 tiles on one
    node, a single tile on the other."""
    a = np.full((npx, npy), heavy_owner, dtype=np.int64)
    a[0, 0] = light_owner
    return a


# -- work_realloc ----------------------------------------------------------
def test_work_realloc_balanced_is_zero():
    busy = np.array([5000.0, 5000.0, 5000.0])
    counts = np.array([5, 5, 5])
    assert (lb.work_realloc(busy, counts) == 0).all()


def test_work_realloc_deadband():
    # deviation below 0.3 * time-per-subdomain moves nothing
    busy = np.array([5000.0, 5100.0])
    counts = np.array([10, 10])  # tps ~ 500, deviation 50 < 150
    assert (lb.work_realloc(busy, counts) == 0).all()


def test_work_realloc_signs():
    busy = np.array([10000.0, 400.0])
    counts = np.array([24, 1])
    r = lb.work_realloc(busy, counts)
    assert r[0] < 0 and r[1] > 0  # overloaded gives, idle takes


# -- rebalance loop --------------------------------------------------------
def test_rebalance_converges_from_reference_fixture():
    a = imbalanced_map()
    tele = lb.WorkTelemetry(2)
    for _ in range(6):  # a few nbalance windows, like the reference's nt=45/nbalance=10
        busy = tele.busy_rates(a)
        ok, _ = lb.balance_check(busy)
        if ok:
            break
        a = lb.rebalance_assignment(a, busy)
    ok, max_diff = lb.balance_check(tele.busy_rates(a))
    counts = np.bincount(a.ravel(), minlength=2)
    assert ok, f"not balanced: {counts}, max_diff={max_diff}"
    assert abs(counts[0] - counts[1]) <= 3


def test_rebalance_never_empties_a_device():
    a = imbalanced_map()
    for _ in range(10):
        a = lb.rebalance_assignment(a, lb.WorkTelemetry(2).busy_rates(a))
        assert (np.bincount(a.ravel(), minlength=2) >= 1).all()


def test_rebalance_four_owners():
    # the reference's load_balance_25s_4n.txt scenario: 4 owners, uneven mix
    rng = np.random.default_rng(3)
    a = rng.integers(0, 4, size=(5, 5))
    a[:3, :] = 2  # make owner 2 heavy
    tele = lb.WorkTelemetry(4)
    for _ in range(8):
        busy = tele.busy_rates(a)
        if lb.balance_check(busy)[0]:
            break
        a = lb.rebalance_assignment(a, busy)
    ok, max_diff = lb.balance_check(tele.busy_rates(a))
    assert ok, f"max_diff={max_diff}, counts={np.bincount(a.ravel(), minlength=4)}"


def test_balance_report_format(capsys):
    busy = np.array([5000.0, 5000.0])
    ok = lb.print_balance_report(busy, np.zeros((2, 2), dtype=np.int64))
    out = capsys.readouterr().out
    assert ok
    assert "Testing load balance:" in out
    assert "Expected busy rate 5000.0" in out
    assert "Load balanced correctly" in out


# -- elastic executor ------------------------------------------------------
def test_elastic_matches_oracle_default_placement():
    s = ElasticSolver2D(10, 10, 5, 5, nt=40, eps=5, k=0.5, dt=0.0005, dh=0.02)
    s.test_init()
    s.do_work()
    assert s.error_l2 / (50 * 50) <= 1e-6


def test_elastic_horizon_exceeds_tile():
    # eps=10 > tile edge 5: multi-ring halo assembly (reference nx<=eps path)
    s = ElasticSolver2D(5, 5, 5, 5, nt=40, eps=10, k=0.2, dt=0.0005, dh=0.02)
    s.test_init()
    s.do_work()
    assert s.error_l2 / (25 * 25) <= 1e-6


def test_elastic_placement_independence():
    """Same problem, different placements -> bit-identical results (the
    framework's determinism/race-freedom check, SURVEY.md section 5)."""
    def run(assignment):
        s = ElasticSolver2D(5, 5, 4, 4, nt=10, eps=3, dt=0.0005, dh=0.02,
                            assignment=assignment)
        s.test_init()
        return s.do_work()

    ndev = len(jax.devices())
    a = default_assignment(4, 4, ndev)
    b = np.zeros((4, 4), dtype=np.int64)  # everything on device 0
    assert np.array_equal(run(a), run(b))


def test_elastic_rebalances_and_stays_correct():
    """The reference's load-balance acceptance flow: start deliberately
    imbalanced, rebalance every 10 steps during a 45-step run, end balanced
    AND numerically correct (migration moves bits, never recomputes)."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    # k chosen for forward-Euler stability: dt * c * dh^2 * Wsum ~ 0.8 < 2
    s = ElasticSolver2D(5, 5, 5, 5, nt=45, eps=2, nbalance=10,
                        k=0.2, dt=0.0005, dh=0.02,
                        assignment=imbalanced_map(), devices=jax.devices()[:2])
    s.test_init()
    s.do_work()
    assert s.error_l2 / (25 * 25) <= 1e-6
    ok, max_diff = lb.balance_check(s.busy_rates())
    assert ok, f"max busy deviation {max_diff} > {lb.ACCEPT_MAX_DEVIATION}"
    counts = np.bincount(s.assignment.ravel(), minlength=2)
    assert counts.min() >= 10  # 25 tiles, 2 devices: near-even split


def test_measured_telemetry_normalization():
    tele = lb.MeasuredTelemetry(3)
    tele.record(0, 0.2)
    tele.record(1, 0.1)
    tele.record(0, 0.2)  # accumulates: 0.4, 0.1, 0.0
    busy = tele.busy_rates()
    assert busy[0] == 10000.0 and busy[1] == 2500.0 and busy[2] == 0.0
    tele.reset()
    assert (tele.busy_rates() == 0).all()


def test_elastic_measured_rebalance_from_imbalanced_map():
    """VERDICT item 4: the balancer must converge on OBSERVED busy rates.

    Default telemetry is now MeasuredTelemetry — real per-device wall-clock,
    no injected speed model.  Start from the reference's 24-of-25 fixture
    shape; the measured imbalance (one device genuinely doing 24x the work)
    must drive the transfer loop to a near-even split.
    """
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    s = ElasticSolver2D(5, 5, 5, 5, nt=45, eps=2, nbalance=10,
                        k=0.2, dt=0.0005, dh=0.02,
                        assignment=imbalanced_map(), devices=jax.devices()[:2])
    s.test_init()
    s.do_work()
    assert isinstance(s.telemetry, lb.MeasuredTelemetry)
    assert s.error_l2 / (25 * 25) <= 1e-6
    counts = np.bincount(s.assignment.ravel(), minlength=2)
    assert counts.min() >= 8, f"measured rebalance did not converge: {counts}"


class _DraggedDeviceSolver(ElasticSolver2D):
    """Test double: tiles on ``slow_device`` cost extra VIRTUAL time.

    The original version interposed a real ``sleep`` and asserted on real
    ``perf_counter`` measurements; under host load mid-suite the noise
    floor crossed the drag and the busy-rate assertion flaked (CHANGES.md
    PR 3).  The executor's measurement clock is injectable exactly for
    this: the solver measures through a virtual clock that only the tile
    hook advances — per-tile cost and the slow device's drag are then
    DETERMINISTIC, the rebalance loop sees the same rates every run, and
    the telemetry/measurement plumbing is still exercised end to end
    (same ``record``/``busy_rates``/``reset`` path, same serialized
    measured windows)."""

    slow_device = 1
    base_s = 0.002  # virtual per-tile cost on every device
    drag_s = 0.006  # extra virtual cost per tile on the slow device

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._vclock = 0.0
        self._measure_clock = lambda: self._vclock

    def _tile_hook(self, key):
        self._vclock += self.base_s
        if int(self.assignment[key]) == self.slow_device:
            self._vclock += self.drag_s


def test_elastic_measured_rebalance_detects_genuinely_slow_device():
    """A device slowed in MEASURED time (virtual clock — deterministic,
    see _DraggedDeviceSolver) sheds tiles, and the final measured busy
    rates meet the reference's <=1500/10000 acceptance criterion
    (src/2d_nonlocal_distributed.cpp:647-686).  The repeat loop proves
    the deflake: every run must converge to the SAME assignment and
    rates — there is no wall-clock left to race."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    final_assignments = []
    for repeat in range(2):
        s = _DraggedDeviceSolver(4, 4, 6, 6, nt=81, eps=2, nbalance=10,
                                 k=0.2, dt=0.0005, dh=0.02,
                                 assignment=default_assignment(6, 6, 2),
                                 devices=jax.devices()[:2])
        s.test_init()
        s.do_work()
        counts = np.bincount(s.assignment.ravel(), minlength=2)
        assert counts[s.slow_device] < counts[1 - s.slow_device], counts
        ok, max_diff = lb.balance_check(s.busy_rates())
        assert ok, (f"run {repeat}: measured busy deviation {max_diff} > "
                    f"{lb.ACCEPT_MAX_DEVIATION}")
        assert s.error_l2 / (24 * 24) <= 1e-6
        final_assignments.append(np.array(s.assignment))
    assert np.array_equal(*final_assignments), \
        "virtual-clock measurement must be run-to-run deterministic"


def test_elastic_fused_equals_general_assembly():
    """The fused 3x3 concat+step path must be bit-identical to the general
    rectangle-walk assembly (same values, same op, same device placement)."""
    def run(force_general):
        s = ElasticSolver2D(8, 8, 3, 3, nt=12, eps=3, k=0.5, dt=0.0005,
                            dh=0.02)
        if force_general:
            s._use_fused = False
        s.test_init()
        return s.do_work()

    assert np.array_equal(run(False), run(True))


def test_elastic_heterogeneous_speeds():
    """A 3x-slower device should end up with ~1/3 the tiles of the fast one."""
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    tele = lb.WorkTelemetry(2, speed_factors=np.array([1.0, 3.0]))
    s = ElasticSolver2D(4, 4, 6, 6, nt=61, eps=2, nbalance=10,
                        k=0.2, dt=0.0005, dh=0.02,
                        assignment=default_assignment(6, 6, 2),
                        devices=jax.devices()[:2], telemetry=tele)
    s.test_init()
    s.do_work()
    counts = np.bincount(s.assignment.ravel(), minlength=2)
    # fair split is 27/9 (so busy is equal); require clear movement that way
    assert counts[1] < counts[0]
    ok, max_diff = lb.balance_check(s.busy_rates())
    assert ok, f"max busy deviation {max_diff}"
    assert s.error_l2 / (24 * 24) <= 1e-6


def test_windowed_measurement_overlaps_nonwindow_steps():
    """VERDICT r2 #5: with nbalance set, only the measure_window steps
    feeding each rebalance are measured (serialized); all other steps take
    the fully overlapped dispatch path."""
    calls = {"measured": 0, "overlapped": 0}

    class Probe(ElasticSolver2D):
        def _step_all_measured(self, t):
            calls["measured"] += 1
            return super()._step_all_measured(t)

        def _step_all_overlapped(self, t):
            calls["overlapped"] += 1
            return super()._step_all_overlapped(t)

    s = Probe(4, 4, 4, 4, nt=20, eps=2, nbalance=10, measure_window=3,
              k=0.2, dt=0.0005, dh=0.02)
    s.use_gang = False  # probe the per-device dispatch path (gang fallback)
    s.test_init()
    s.do_work()
    # windows (nbalance=10, W=3): {8,9,10} and {18,19} within t<20
    assert calls["measured"] == 5, calls
    assert calls["overlapped"] == 15, calls
    assert s.error_l2 / (16 * 16) <= 1e-6


def test_gang_covers_nonwindow_steps_with_zero_host_dispatch():
    """Round 3: with gang scheduling (the default), every non-window step
    runs inside a fused SPMD scan — no per-device or per-tile host dispatch
    outside the measurement windows."""
    calls = {"measured": 0, "overlapped": 0, "batched": 0, "stretches": []}

    class Probe(ElasticSolver2D):
        def _step_all_measured(self, t):
            calls["measured"] += 1
            return super()._step_all_measured(t)

        def _step_all_overlapped(self, t):
            calls["overlapped"] += 1
            return super()._step_all_overlapped(t)

        def _step_device_batched(self, d, t):
            calls["batched"] += 1
            return super()._step_device_batched(d, t)

    s = Probe(4, 4, 4, 4, nt=20, eps=2, nbalance=10, measure_window=3,
              k=0.2, dt=0.0005, dh=0.02)
    s.test_init()
    s.do_work()
    # measured windows unchanged; the other 15 steps ran in gang stretches
    assert calls["measured"] == 5, calls
    assert calls["overlapped"] == 0, calls
    assert calls["batched"] == 0, calls
    assert s.error_l2 / (16 * 16) <= 1e-6


def test_batched_dispatch_one_call_per_device_per_step():
    """VERDICT r2 #7: the overlapped fused path dispatches ONE batched jit
    call per device per step, not one per tile."""
    calls = {"batched": 0, "tile": 0}

    class Probe(ElasticSolver2D):
        def _step_device_batched(self, d, t):
            calls["batched"] += 1
            return super()._step_device_batched(d, t)

        def _step_tile(self, key, t):
            calls["tile"] += 1
            return super()._step_tile(key, t)

    ndev = min(2, len(jax.devices()))
    s = Probe(4, 4, 4, 4, nt=10, eps=2, k=0.2, dt=0.0005, dh=0.02,
              devices=jax.devices()[:ndev])
    s.use_gang = False  # probe the per-device dispatch path (gang fallback)
    s.test_init()
    s.do_work()
    assert calls["tile"] == 0, calls  # no per-tile dispatch on this path
    assert calls["batched"] == 10 * ndev, calls
    assert s.error_l2 / (16 * 16) <= 1e-6
