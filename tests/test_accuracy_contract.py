"""The f64 accuracy contract at the TPU dtype (f32).

The reference is all-double (src/2d_nonlocal_distributed.cpp:136) and every
test asserts error_l2/#points <= 1e-6 at t=nt (:1346).  The TPU fast path
computes in f32 — these tests demonstrate that the contract SURVIVES f32 over
multi-step runs, for every evaluation method, at the largest config the
reference's own tables exercise (200x200, tests/2d.txt row 4) and against the
f64 oracle on random states (the bench.py gate's stronger form).

conftest enables x64, so dtype=float32 below genuinely forces the f32 path.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.ops.nonlocal_op import NonlocalOp2D
from tests.cases import L2_THRESHOLD


@pytest.mark.parametrize("method", ["conv", "sat"])
def test_f32_holds_contract_200sq(method):
    # largest reference-table config: 200x200, 40 steps, eps=5 (tests/2d.txt)
    s = Solver2D(200, 200, 40, eps=5, k=1.0, dt=0.0005, dh=0.02,
                 backend="jit", method=method, dtype=jnp.float32)
    s.test_init()
    s.do_work()
    assert s.error_l2 / (200 * 200) <= L2_THRESHOLD


def test_f32_holds_contract_pallas():
    # pallas runs interpreted off-TPU; keep the grid tabletop-sized
    s = Solver2D(50, 50, 45, eps=5, k=1.0, dt=0.0005, dh=0.02,
                 backend="jit", method="pallas", dtype=jnp.float32)
    s.test_init()
    s.do_work()
    assert s.error_l2 / (50 * 50) <= L2_THRESHOLD


def test_f32_long_horizon_contract():
    # eps=10 on 50x50: wide-horizon row (tests/2d.txt row 3) in f32
    s = Solver2D(50, 50, 200, eps=10, k=1.0, dt=0.0005, dh=0.02,
                 backend="jit", method="sat", dtype=jnp.float32)
    s.test_init()
    s.do_work()
    assert s.error_l2 / (50 * 50) <= L2_THRESHOLD


@pytest.mark.parametrize("method", ["conv", "sat", "pallas"])
def test_f32_multistep_drift_vs_f64_oracle(method):
    """50 free-decay steps from a random state: f32 vs the f64 oracle.

    This is bench.py's accuracy gate in test form (same physics scaled down:
    eps=8, dh=1/N, stability-bounded dt), isolating pure dtype drift with no
    manufactured-solution discretization error in the comparison.
    """
    n, nsteps = 128, 50
    probe = NonlocalOp2D(8, k=1.0, dt=1.0, dh=1.0 / n, method=method)
    dt = 0.8 / (probe.c * probe.dh * probe.dh * probe.wsum)
    op = NonlocalOp2D(8, k=1.0, dt=dt, dh=1.0 / n, method=method)

    rng = np.random.default_rng(0)
    u0 = rng.normal(size=(n, n))
    ref = u0.copy()
    for _ in range(nsteps):
        ref = ref + op.dt * op.apply_np(ref)
    got = jnp.asarray(u0, jnp.float32)
    for _ in range(nsteps):
        got = got + op.dt * op.apply(got)
    l2_per_n = float(np.sum((np.asarray(got) - ref) ** 2)) / (n * n)
    assert l2_per_n <= L2_THRESHOLD


def test_f32_drift_flat_across_grid_sizes():
    """VERDICT r2 #4: evidence that the bench's 2048^2 runtime gate bounds
    the 4096^2 headline config — the per-point f32 drift vs the f64 oracle
    must stay flat (not grow) as the grid scales 256 -> 512 -> 1024 with the
    bench's physics (eps=8, dh=1/N, stability-bounded dt).
    """
    drifts = {}
    rng = np.random.default_rng(0)
    for n in (256, 512, 1024):
        nsteps = 10
        probe = NonlocalOp2D(8, k=1.0, dt=1.0, dh=1.0 / n, method="sat")
        dt = 0.8 / (probe.c * probe.dh * probe.dh * probe.wsum)
        op = NonlocalOp2D(8, k=1.0, dt=dt, dh=1.0 / n, method="sat")
        u0 = rng.normal(size=(n, n))
        ref = u0.copy()
        for _ in range(nsteps):
            ref = ref + op.dt * op.apply_np(ref)
        got = jnp.asarray(u0, jnp.float32)
        for _ in range(nsteps):
            got = got + op.dt * op.apply(got)
        drifts[n] = float(np.sum((np.asarray(got) - ref) ** 2)) / (n * n)
    # every size holds the contract with orders of magnitude to spare...
    for n, d in drifts.items():
        assert d <= L2_THRESHOLD * 1e-6, f"L2/N at {n}^2 = {d:.3e}"
    # ...and doubling the grid does not inflate per-point drift (no
    # size-coupled error growth; 10x headroom for noise)
    assert drifts[1024] <= 10 * drifts[256], drifts


def test_contract_at_headline_scale():
    """VERDICT r2 weak #3 closed at FULL scale: the f32 accuracy claim is
    demonstrated at the headline 4096^2 eps=8 config itself, not
    extrapolated.  The pallas interpreter executes the exact summation
    order the compiled Mosaic kernel uses, so this CPU run is
    representative of the TPU arithmetic.  (~20s: one f32 interpreter
    solve + one f64 sat solve.)"""
    import jax.numpy as jnp

    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        NonlocalOp2D,
        make_multi_step_fn,
    )

    GRID, EPS, STEPS = 4096, 8, 15
    probe = NonlocalOp2D(EPS, k=1.0, dt=1.0, dh=1.0 / GRID, method="pallas")
    dt = 0.8 / (probe.c * probe.dh * probe.dh * probe.wsum)
    rng = np.random.default_rng(0)
    u0 = rng.normal(size=(GRID, GRID))

    op32 = NonlocalOp2D(EPS, k=1.0, dt=dt, dh=1.0 / GRID, method="pallas")
    u32 = np.asarray(
        make_multi_step_fn(op32, STEPS, dtype=jnp.float32)(
            jnp.asarray(u0, jnp.float32), jnp.int32(0)), np.float64)

    op64 = NonlocalOp2D(EPS, k=1.0, dt=dt, dh=1.0 / GRID, method="sat")
    u64 = np.asarray(
        make_multi_step_fn(op64, STEPS)(jnp.asarray(u0), jnp.int64(0)))

    d = u32 - u64
    l2_per_n = float(np.sum(d * d)) / GRID / GRID
    assert l2_per_n <= 1e-6, l2_per_n   # the reference's contract
    assert l2_per_n < 1e-15             # and the measured headroom class
