"""Driver entry-point contracts (__graft_entry__.py).

entry() must never initialize the real backend in-process: probes run in
killable subprocesses and example args are NumPy, so a wedged chip (which
hangs jax.devices() with no exception — the round-1/2 artifact killer)
cannot hang the driver's compile-check inside entry() itself.
"""

import os
import subprocess
import sys
import time

import numpy as np

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_entry_returns_numpy_args_and_jits_on_cpu(monkeypatch):
    # short probe budget: the ambient backend may be a wedged TPU; the
    # contract under test is "entry() returns promptly with jittable parts"
    monkeypatch.setenv("GRAFT_PALLAS_PROBE_S", "5")
    sys.path.insert(0, REPO)
    try:
        import __graft_entry__ as ge

        t0 = time.time()
        fn, args = ge.entry()
        took = time.time() - t0
        assert took < 60, f"entry() took {took:.0f}s with a 5s probe budget"
        assert isinstance(args[0], np.ndarray)  # no backend init in entry()
        out = jax.jit(fn)(*args)  # conftest pins this process to CPU
        assert out.shape == (512, 512)
    finally:
        sys.path.remove(REPO)


def test_dryrun_multichip_subprocess_isolation():
    # dryrun must not disturb the caller's JAX config (ADVICE r2); cheap to
    # check from a child so this test doesn't depend on conftest state
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import jax\n"
        "before = jax.config.jax_platforms\n"
        "import __graft_entry__ as ge\n"
        "ge.dryrun_multichip(4)\n"
        "assert jax.config.jax_platforms == before, 'caller config mutated'\n"
        "print('ok')\n" % REPO
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env=env, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    assert "ok" in proc.stdout


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
