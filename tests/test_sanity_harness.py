"""The compiled-mode sanity sweep must never kill a client mid-compile.

The 2026-07-30 wedge showed the failure shape: one config hung, a blind
in-process watchdog killed the whole sweep (a mid-compile kill is itself a
wedge trigger, docs/bench/README.md "Wedge trigger"), and the refresh then
ran unprotected tools against the dead tunnel.  tools/tpu_sanity.py now
runs each check in its own subprocess under a two-phase budget; these
tests drive the parent as a black box on CPU with injected hangs and
assert the kill policy:

  * an init-phase hang (no PHASE:init-ok line) is killed at the init
    budget and aborts the sweep naming the config — safe phase, same kill
    bench.py's probes use;
  * a compile/run-phase hang (PHASE printed, then wedged) is NOT killed
    at the check budget — only the hard cap may kill it, and the abort
    names the config and the cap.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SANITY = os.path.join(REPO, "tools", "tpu_sanity.py")


def run_sweep(env_extra, timeout=300):
    env = dict(os.environ)
    env.pop("SANITY_FAULT", None)
    env.update({"BENCH_PLATFORM": "cpu", "SANITY_TEST_MODE": "1"}, **env_extra)
    return subprocess.run(
        [sys.executable, SANITY], capture_output=True, text=True, env=env,
        timeout=timeout,
    )


def test_init_hang_is_killed_at_init_budget_and_names_config():
    # init budget well above a loaded machine's real import+init time (~5s)
    # so only the injected hang — which never prints PHASE — trips it
    proc = run_sweep({
        "SANITY_FAULT": "hang_init",
        "SANITY_FAULT_INDEX": "1",
        "SANITY_INIT_BUDGET_S": "25",
        "SANITY_CHECK_BUDGET_S": "60",
        "SANITY_HARD_CAP_S": "120",
    })
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert "HANG 2d 200^2 eps=5 (init)" in proc.stdout
    # the sweep stopped: the check after the hung one never ran
    assert "2d 50^2 eps=10" not in proc.stdout
    # check 0 still passed before the hang
    assert "ok   2d 50^2 eps=5" in proc.stdout


def test_check_phase_hang_waits_past_budget_then_hard_cap_kills():
    proc = run_sweep({
        "SANITY_FAULT": "hang_check",
        "SANITY_FAULT_INDEX": "0",
        "SANITY_INIT_BUDGET_S": "60",
        "SANITY_CHECK_BUDGET_S": "6",
        "SANITY_HARD_CAP_S": "18",
    }, timeout=240)
    assert proc.returncode == 3, proc.stdout + proc.stderr
    # the soft budget warned instead of killing
    assert "NOT killing" in proc.stdout
    # only the hard cap ended it, and the abort names config and phase
    assert "HANG 2d 50^2 eps=5 (compile/run > 18s hard cap)" in proc.stdout


@pytest.mark.slow  # ~32 s: the full interpreted sweep end to end.  Marked
# slow (PR 2) to hold the 870 s tier-1 budget; the kill/abort policy
# tests above stay in tier-1.  Run `pytest -m slow` for this one.
def test_healthy_interpreted_sweep_is_labeled():
    # no faults: first check passes and the off-TPU disclaimer is printed
    # (run just past the first check, then the backend note must be there)
    proc = run_sweep({
        "SANITY_FAULT": "hang_init",   # hang check 1 so the run stays short
        "SANITY_FAULT_INDEX": "1",
        "SANITY_INIT_BUDGET_S": "25",
    })
    assert "backend: cpu" in proc.stdout
    assert "kernels run interpreted" in proc.stdout


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
