"""Sharded unstructured operator: multi-device == single-device to 1e-12.

BASELINE config 5 / VERDICT item 8: the edge list is partitioned by
target-node shard over a 1D device mesh; state moves by all_gather (the
unstructured halo), scatter-adds stay device-local.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nonlocalheatequation_tpu.ops.unstructured import (
    ShardedUnstructuredOp,
    UnstructuredNonlocalOp,
    UnstructuredSolver,
)


def jittered_cloud(m=16, seed=0):
    """m x m grid nodes jittered 20%: irregular but horizon-covered."""
    rng = np.random.default_rng(seed)
    h = 1.0 / m
    xs, ys = np.meshgrid(np.arange(m) * h, np.arange(m) * h, indexing="ij")
    pts = np.stack([xs.ravel(), ys.ravel()], axis=1)
    pts += rng.uniform(-0.2 * h, 0.2 * h, pts.shape)
    return pts, h


def cloud_op(m=32, seed=0):
    """The canonical multihost-test operator: every process (and the
    parent) must build bit-identical physics from the same seed — the
    multi-controller init contract.  One definition so the constants
    cannot drift between the crash writer and the resume readers."""
    pts, h = jittered_cloud(m=m, seed=seed)
    return UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-6, vol=h * h)


@pytest.mark.parametrize("ndev", [1, 8])
def test_sharded_apply_matches_single_device(ndev):
    pts, h = jittered_cloud()
    eps = 3.05 * h * (1.0 + 0.2 * np.sin(7.0 * pts[:, 0]))  # variable horizon
    op = UnstructuredNonlocalOp(pts, eps, k=1.0, dt=1e-5, vol=h * h)
    sharded = ShardedUnstructuredOp(op, devices=jax.devices()[:ndev])

    rng = np.random.default_rng(1)
    u = rng.normal(size=op.n)
    a = op.apply_np(u)
    b = np.asarray(sharded.apply(jnp.asarray(u)))
    assert np.abs(a - b).max() < 1e-12


def test_sharded_apply_uneven_block_padding():
    # n = 225 over 8 devices: B = 29, last block short -> exercises padding
    pts, h = jittered_cloud(m=15, seed=3)
    op = UnstructuredNonlocalOp(pts, 2.5 * h, k=1.0, dt=1e-5, vol=h * h)
    assert op.n % len(jax.devices()) != 0
    sharded = ShardedUnstructuredOp(op)
    rng = np.random.default_rng(2)
    u = rng.normal(size=op.n)
    assert np.abs(op.apply_np(u) - np.asarray(sharded.apply(jnp.asarray(u)))).max() < 1e-12


def test_sharded_solver_matches_single_device_solve():
    pts, h = jittered_cloud(m=12, seed=5)
    kw = dict(k=0.5, dt=1e-5, vol=h * h)
    op = UnstructuredNonlocalOp(pts, 2.8 * h, **kw)
    single = UnstructuredSolver(op, nt=20)
    single.test_init()
    us = single.do_work()

    sharded = UnstructuredSolver(ShardedUnstructuredOp(op), nt=20)
    sharded.test_init()
    um = sharded.do_work()
    assert np.abs(us - um).max() < 1e-12
    assert sharded.error_l2 / op.n <= 1e-6


def test_sharded_manufactured_contract():
    pts, h = jittered_cloud(m=16, seed=8)
    op = UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-5, vol=h * h)
    s = UnstructuredSolver(ShardedUnstructuredOp(op), nt=30)
    s.test_init()
    s.do_work()
    assert s.error_l2 / op.n <= 1e-6


def test_export_halo_bit_identical_to_full_gather():
    """The boundary-export halo reads the same addends in the same order as
    the full-state gather -> bit-identical results."""
    pts, h = jittered_cloud(m=16, seed=11)
    op = UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-5, vol=h * h)
    a = ShardedUnstructuredOp(op, halo="export")
    b = ShardedUnstructuredOp(op, halo="gather")
    assert a.halo_mode == "export" and b.halo_mode == "gather"
    rng = np.random.default_rng(4)
    u = rng.normal(size=op.n)
    ra = np.asarray(a.apply(jnp.asarray(u)))
    rb = np.asarray(b.apply(jnp.asarray(u)))
    assert np.array_equal(ra, rb)
    assert np.abs(ra - op.apply_np(u)).max() < 1e-12


def test_export_halo_auto_selection():
    """auto picks export for a locality-preserving node order (the grid's
    row-major order: remote refs are near-boundary rows) and falls back to
    the full gather when a random permutation destroys locality."""
    # blocks must be thick relative to eps for a halo to exist: m=128 over
    # 8 shards gives 16 grid rows per block, eps=3h reaches ~3 rows deep
    pts, h = jittered_cloud(m=128, seed=13)
    op = UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-5, vol=h * h)
    # layout="edges": this test targets the edge layout's halo machinery
    # (plain auto now picks the offsets layout on a jittered grid)
    s1 = ShardedUnstructuredOp(op, layout="edges")
    if len(jax.devices()) >= 8:
        assert s1.halo_mode == "export", s1.halo_comm_ratio
        assert s1.halo_comm_ratio < 0.5

    pts, h = jittered_cloud(m=16, seed=13)
    rng = np.random.default_rng(5)
    perm = rng.permutation(len(pts))
    op2 = UnstructuredNonlocalOp(pts[perm], 3.0 * h, k=1.0, dt=1e-5,
                                 vol=h * h)
    s2 = ShardedUnstructuredOp(op2)
    if len(jax.devices()) >= 8:
        assert s2.halo_mode == "gather", s2.halo_comm_ratio
    # both still correct regardless of mode
    u = rng.normal(size=op2.n)
    assert np.abs(op2.apply_np(u)
                  - np.asarray(s2.apply(jnp.asarray(u)))).max() < 1e-12


def test_export_halo_uneven_padding():
    """Short last block + export halo: pad nodes are never exported."""
    pts, h = jittered_cloud(m=15, seed=17)  # 225 nodes, B=29 on 8 devices
    op = UnstructuredNonlocalOp(pts, 2.5 * h, k=1.0, dt=1e-5, vol=h * h)
    s = ShardedUnstructuredOp(op, halo="export")
    rng = np.random.default_rng(6)
    u = rng.normal(size=op.n)
    assert np.abs(op.apply_np(u)
                  - np.asarray(s.apply(jnp.asarray(u)))).max() < 1e-12


# -- superstep (one K*pad-wide ring exchange per K steps, offsets form) ----


def _offsets_cloud_4dev(m=32, seed=0):
    """Jittered grid whose offsets form fits K=2 on 4 devices (B=256,
    pads ~97)."""
    pts, h = jittered_cloud(m=m, seed=seed)
    op = UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-6, vol=h * h)
    sh = ShardedUnstructuredOp(op, devices=jax.devices()[:4])
    assert sh.layout == "offsets"
    return op, sh


def test_sharded_superstep_engages_and_matches_oracle():
    """K=2 on the sharded offsets form: the K-block program must actually
    build (probed), remainder steps run per-step (nt=7), and the result
    matches the serial oracle and the K=1 run — the sharded-unstructured
    leg of the communication-avoiding schedule (grid SPMD and gang
    elastic being the other two)."""
    op, sh = _offsets_cloud_4dev()
    assert sh.superstep_fits(2) and not sh.superstep_fits(5)

    o = UnstructuredSolver(op, nt=7, backend="oracle")
    o.test_init()
    uo = o.do_work()

    built = []
    real = ShardedUnstructuredOp.make_superstep

    def probed(self, *a, **kw):
        built.append(a[0])
        return real(self, *a, **kw)

    ShardedUnstructuredOp.make_superstep = probed
    try:
        outs = {}
        for K in (1, 2):
            s = UnstructuredSolver(sh, nt=7, backend="jit", superstep=K)
            s.test_init()
            outs[K] = s.do_work()
            assert s.error_l2 / op.n <= 1e-6
    finally:
        ShardedUnstructuredOp.make_superstep = real
    assert built == [2], "superstep program did not engage"
    assert np.abs(outs[2] - uo).max() < 1e-12
    assert np.abs(outs[1] - outs[2]).max() < 1e-12


def test_sharded_superstep_input_path_and_checkpoint_chunks(tmp_path):
    """Free-decay input + checkpoint cadence (chunked runner: 3+3+1
    segments, so both a clean K-block chunk and remainders inside chunks
    run) must agree with the K=1 run; the checkpoint resumes."""
    op, sh = _offsets_cloud_4dev(seed=4)
    rng = np.random.default_rng(7)
    u0 = rng.normal(size=op.n)
    outs = {}
    for K in (1, 2):
        ck = tmp_path / f"ck{K}.npz"
        s = UnstructuredSolver(sh, nt=7, backend="jit", superstep=K,
                               checkpoint_path=str(ck), ncheckpoint=3)
        s.input_init(u0)
        outs[K] = s.do_work()
        assert ck.exists()
    assert np.abs(outs[1] - outs[2]).max() < 1e-12


def test_sharded_superstep_honesty_gates():
    """The flag must refuse every configuration where the schedule cannot
    engage: unsharded op, edges layout, K*pad > block."""
    pts, h = jittered_cloud(m=16, seed=2)
    op = UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-6, vol=h * h)
    with pytest.raises(ValueError, match="Sharded"):
        UnstructuredSolver(op, nt=4, superstep=2)
    # 8 devices on the small cloud: B=32 < 2*pads — does not fit
    sh8 = ShardedUnstructuredOp(op)
    if sh8.layout == "offsets":
        with pytest.raises(ValueError, match="does not fit"):
            UnstructuredSolver(sh8, nt=4, superstep=2)
    # shuffled cloud: offsets cannot cover -> edges layout -> refused
    perm = np.random.default_rng(0).permutation(op.n)
    op_sh = UnstructuredNonlocalOp(pts[perm], 3.0 * h, k=1.0, dt=1e-6,
                                   vol=h * h)
    shs = ShardedUnstructuredOp(op_sh, devices=jax.devices()[:2])
    if shs.layout != "offsets":
        with pytest.raises(ValueError, match="does not fit"):
            UnstructuredSolver(shs, nt=4, superstep=2)


def test_sharded_superstep_refuses_cadence_with_no_k_block(tmp_path):
    """Checkpoint cadence shorter than K makes every segment too short for
    a K-block: the run must refuse (same honesty rule as the elastic
    gates), not silently step per-exchange under the flag."""
    op, sh = _offsets_cloud_4dev(seed=9)
    s = UnstructuredSolver(sh, nt=8, backend="jit", superstep=2,
                           checkpoint_path=str(tmp_path / "c.npz"),
                           ncheckpoint=1)
    s.test_init()
    with pytest.raises(RuntimeError, match="cannot engage"):
        s.do_work()


def test_plan_default_literals_match_build_plan_signature():
    """The windowed worthwhileness gate calls _plan_search with literal
    defaults so its search can be reused by the default windowed_plan()
    build; those literals must track build_plan's signature defaults."""
    import inspect

    from nonlocalheatequation_tpu.ops.windowed import build_plan

    sig = inspect.signature(build_plan)
    assert sig.parameters["bm"].default == 128
    assert sig.parameters["wmax"].default == 4096
    assert sig.parameters["max_overflow_frac"].default == 0.02
    assert sig.parameters["order"].default == "morton"
    assert sig.parameters["windows"].default == 2


def test_sharded_superstep_checkpoint_portable_across_schedules(tmp_path):
    """Same schedule-agnostic checkpoint contract for the ring superstep:
    written by a K=2 run, resumed per-step (and vice versa), equal to the
    uninterrupted trajectory."""
    op, sh = _offsets_cloud_4dev(seed=11)
    straight = UnstructuredSolver(sh, nt=8, backend="jit")
    straight.test_init()
    u_ref = straight.do_work()

    for k_write, k_resume in ((2, 1), (1, 2)):
        ck = tmp_path / f"ck-{k_write}-{k_resume}.npz"
        w = UnstructuredSolver(sh, nt=8, backend="jit", superstep=k_write,
                               checkpoint_path=str(ck), ncheckpoint=4)
        w.test_init()
        w.nt = 6  # "crash" after step 6: the checkpoint on disk is t=4
        w.do_work()
        r = UnstructuredSolver(sh, nt=8, backend="jit", superstep=k_resume)
        r.test_init()
        r.resume(str(ck))
        assert r.t0 == 4
        u_res = r.do_work()
        d = np.abs(u_res - u_ref).max()
        assert d < 1e-12, f"K={k_write}->K={k_resume} resume drifts {d:.2e}"


def test_sharded_3d_cloud_offsets_and_superstep():
    """The sharded operator is dimension-agnostic: a 3D jittered cloud in
    natural order keeps the offsets (DIA) layout, matches the NumPy
    oracle across shards, and (block permitting) runs the ring superstep
    too."""
    rng = np.random.default_rng(3)
    m = 12
    h = 1.0 / m
    ax = np.arange(m) * h
    gx, gy, gz = np.meshgrid(ax, ax, ax, indexing="ij")
    pts = np.stack([gx.ravel(), gy.ravel(), gz.ravel()], 1)
    pts += rng.uniform(-0.2 * h, 0.2 * h, pts.shape)
    op = UnstructuredNonlocalOp(pts, 2.5 * h, k=1.0, dt=1e-7, vol=h ** 3)
    sh = ShardedUnstructuredOp(op, devices=jax.devices()[:2])
    assert sh.layout == "offsets", sh.layout
    u = rng.normal(size=op.n)
    got = np.asarray(sh.apply(jnp.asarray(u)))
    assert np.abs(got - op.apply_np(u)).max() < 1e-12

    s = UnstructuredSolver(sh, nt=5, backend="jit")
    s.test_init()
    us = s.do_work()
    assert s.error_l2 / op.n <= 1e-6
    if sh.superstep_fits(2):
        ss = UnstructuredSolver(sh, nt=5, backend="jit", superstep=2)
        ss.test_init()
        assert np.abs(ss.do_work() - us).max() < 1e-12
