"""Sharded unstructured operator: multi-device == single-device to 1e-12.

BASELINE config 5 / VERDICT item 8: the edge list is partitioned by
target-node shard over a 1D device mesh; state moves by all_gather (the
unstructured halo), scatter-adds stay device-local.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nonlocalheatequation_tpu.ops.unstructured import (
    ShardedUnstructuredOp,
    UnstructuredNonlocalOp,
    UnstructuredSolver,
)


def jittered_cloud(m=16, seed=0):
    """m x m grid nodes jittered 20%: irregular but horizon-covered."""
    rng = np.random.default_rng(seed)
    h = 1.0 / m
    xs, ys = np.meshgrid(np.arange(m) * h, np.arange(m) * h, indexing="ij")
    pts = np.stack([xs.ravel(), ys.ravel()], axis=1)
    pts += rng.uniform(-0.2 * h, 0.2 * h, pts.shape)
    return pts, h


@pytest.mark.parametrize("ndev", [1, 8])
def test_sharded_apply_matches_single_device(ndev):
    pts, h = jittered_cloud()
    eps = 3.05 * h * (1.0 + 0.2 * np.sin(7.0 * pts[:, 0]))  # variable horizon
    op = UnstructuredNonlocalOp(pts, eps, k=1.0, dt=1e-5, vol=h * h)
    sharded = ShardedUnstructuredOp(op, devices=jax.devices()[:ndev])

    rng = np.random.default_rng(1)
    u = rng.normal(size=op.n)
    a = op.apply_np(u)
    b = np.asarray(sharded.apply(jnp.asarray(u)))
    assert np.abs(a - b).max() < 1e-12


def test_sharded_apply_uneven_block_padding():
    # n = 225 over 8 devices: B = 29, last block short -> exercises padding
    pts, h = jittered_cloud(m=15, seed=3)
    op = UnstructuredNonlocalOp(pts, 2.5 * h, k=1.0, dt=1e-5, vol=h * h)
    assert op.n % len(jax.devices()) != 0
    sharded = ShardedUnstructuredOp(op)
    rng = np.random.default_rng(2)
    u = rng.normal(size=op.n)
    assert np.abs(op.apply_np(u) - np.asarray(sharded.apply(jnp.asarray(u)))).max() < 1e-12


def test_sharded_solver_matches_single_device_solve():
    pts, h = jittered_cloud(m=12, seed=5)
    kw = dict(k=0.5, dt=1e-5, vol=h * h)
    op = UnstructuredNonlocalOp(pts, 2.8 * h, **kw)
    single = UnstructuredSolver(op, nt=20)
    single.test_init()
    us = single.do_work()

    sharded = UnstructuredSolver(ShardedUnstructuredOp(op), nt=20)
    sharded.test_init()
    um = sharded.do_work()
    assert np.abs(us - um).max() < 1e-12
    assert sharded.error_l2 / op.n <= 1e-6


def test_sharded_manufactured_contract():
    pts, h = jittered_cloud(m=16, seed=8)
    op = UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-5, vol=h * h)
    s = UnstructuredSolver(ShardedUnstructuredOp(op), nt=30)
    s.test_init()
    s.do_work()
    assert s.error_l2 / op.n <= 1e-6


def test_export_halo_bit_identical_to_full_gather():
    """The boundary-export halo reads the same addends in the same order as
    the full-state gather -> bit-identical results."""
    pts, h = jittered_cloud(m=16, seed=11)
    op = UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-5, vol=h * h)
    a = ShardedUnstructuredOp(op, halo="export")
    b = ShardedUnstructuredOp(op, halo="gather")
    assert a.halo_mode == "export" and b.halo_mode == "gather"
    rng = np.random.default_rng(4)
    u = rng.normal(size=op.n)
    ra = np.asarray(a.apply(jnp.asarray(u)))
    rb = np.asarray(b.apply(jnp.asarray(u)))
    assert np.array_equal(ra, rb)
    assert np.abs(ra - op.apply_np(u)).max() < 1e-12


def test_export_halo_auto_selection():
    """auto picks export for a locality-preserving node order (the grid's
    row-major order: remote refs are near-boundary rows) and falls back to
    the full gather when a random permutation destroys locality."""
    # blocks must be thick relative to eps for a halo to exist: m=128 over
    # 8 shards gives 16 grid rows per block, eps=3h reaches ~3 rows deep
    pts, h = jittered_cloud(m=128, seed=13)
    op = UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-5, vol=h * h)
    # layout="edges": this test targets the edge layout's halo machinery
    # (plain auto now picks the offsets layout on a jittered grid)
    s1 = ShardedUnstructuredOp(op, layout="edges")
    if len(jax.devices()) >= 8:
        assert s1.halo_mode == "export", s1.halo_comm_ratio
        assert s1.halo_comm_ratio < 0.5

    pts, h = jittered_cloud(m=16, seed=13)
    rng = np.random.default_rng(5)
    perm = rng.permutation(len(pts))
    op2 = UnstructuredNonlocalOp(pts[perm], 3.0 * h, k=1.0, dt=1e-5,
                                 vol=h * h)
    s2 = ShardedUnstructuredOp(op2)
    if len(jax.devices()) >= 8:
        assert s2.halo_mode == "gather", s2.halo_comm_ratio
    # both still correct regardless of mode
    u = rng.normal(size=op2.n)
    assert np.abs(op2.apply_np(u)
                  - np.asarray(s2.apply(jnp.asarray(u)))).max() < 1e-12


def test_export_halo_uneven_padding():
    """Short last block + export halo: pad nodes are never exported."""
    pts, h = jittered_cloud(m=15, seed=17)  # 225 nodes, B=29 on 8 devices
    op = UnstructuredNonlocalOp(pts, 2.5 * h, k=1.0, dt=1e-5, vol=h * h)
    s = ShardedUnstructuredOp(op, halo="export")
    rng = np.random.default_rng(6)
    u = rng.normal(size=op.n)
    assert np.abs(op.apply_np(u)
                  - np.asarray(s.apply(jnp.asarray(u)))).max() < 1e-12
