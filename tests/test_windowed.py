"""Windowed block-dense unstructured path (ops/windowed.py).

Contract: identical operator to the edge-list/ELL paths (1e-12-close in
f64 — the reduction order differs, same family contract as the grid
kernels' method equivalence), exact under forced window overflow, and the
solver's permuted-space scan must keep chunk-boundary state in original
node order.  Math parity target: the same L as apply_np
(/root/reference/description/problem_description.tex:131-158).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from nonlocalheatequation_tpu.ops.unstructured import (
    UnstructuredNonlocalOp,
    UnstructuredSolver,
)
from nonlocalheatequation_tpu.ops.windowed import build_plan, morton_perm


def _cloud(m, d=2, seed=0, eps_fn=None):
    rng = np.random.default_rng(seed)
    h = 1.0 / m
    axes = [np.arange(m) * h for _ in range(d)]
    grids = np.meshgrid(*axes, indexing="ij")
    pts = np.stack([g.ravel() for g in grids], axis=1)
    pts += rng.uniform(-0.2 * h, 0.2 * h, pts.shape)
    eps = (3.0 * h * (1.0 + 0.2 * np.sin(7.0 * pts[:, 0]))
           if eps_fn is None else eps_fn(pts, h))
    return UnstructuredNonlocalOp(pts, eps, k=1.0, dt=1e-6, vol=h ** d)


def _plan_of(op, **kw):
    return build_plan(op.points, op.eps, op.tgt, op.src, op.edge_w,
                      op.c, op.wsum, **kw)


def test_windowed_matches_oracle_2d():
    op = _cloud(48)
    u = np.random.default_rng(1).normal(size=op.n)
    want = op.apply_np(u)
    got = np.asarray(op.apply(jnp.asarray(u), layout="windowed"))
    assert np.max(np.abs(got - want)) < 1e-12 * max(1.0, np.abs(want).max())


def test_windowed_matches_oracle_3d():
    op = _cloud(12, d=3)
    u = np.random.default_rng(2).normal(size=op.n)
    want = op.apply_np(u)
    got = np.asarray(op.apply(jnp.asarray(u), layout="windowed"))
    assert np.max(np.abs(got - want)) < 1e-12 * max(1.0, np.abs(want).max())


def test_forced_overflow_stays_exact():
    # a tiny wmax forces most edges out of the windows; the residual
    # segment_sum path must keep the operator exact anyway
    op = _cloud(32)
    plan = _plan_of(op, wmax=128)
    assert plan.W == 128
    assert plan.ov_tgt.size > 0
    u = np.random.default_rng(3).normal(size=op.n)
    got = np.asarray(plan.for_dtype(jnp.float64).L(jnp.asarray(u)))
    want = op.apply_np(u)
    assert np.max(np.abs(got - want)) < 1e-12 * max(1.0, np.abs(want).max())


def test_plan_accounts_for_every_edge():
    op = _cloud(32)
    plan = _plan_of(op)
    in_window = int((np.asarray(plan.P) != 0).sum())
    # zero-weight edges can hide in P (none here: J==1, vol>0), so nnz(P)
    # plus the residual list must cover the whole edge set exactly
    assert in_window + plan.ov_tgt.size == len(op.tgt)
    assert 0.0 <= plan.coverage <= 1.0
    assert plan.coverage == pytest.approx(in_window / len(op.tgt))


def test_keep_order_on_premorton_points_is_tight():
    # points already fed in Morton order should yield the same W whether
    # the plan re-sorts or trusts the caller
    op = _cloud(32)
    perm = morton_perm(op.points, float(op.eps.max()))
    op2 = UnstructuredNonlocalOp(op.points[perm], op.eps[perm], k=1.0,
                                 dt=1e-6, vol=1.0 / 32 ** 2)
    plan_keep = _plan_of(op2, order="keep")
    plan_morton = _plan_of(op2)
    assert plan_keep.W == plan_morton.W


def test_n_not_multiple_of_block():
    rng = np.random.default_rng(4)
    pts = rng.uniform(size=(1000, 2))  # not a multiple of 128
    op = UnstructuredNonlocalOp(pts, 0.08, k=1.0, dt=1e-6, vol=1e-3)
    u = rng.normal(size=op.n)
    got = np.asarray(op.apply(jnp.asarray(u), layout="windowed"))
    want = op.apply_np(u)
    assert np.max(np.abs(got - want)) < 1e-12 * max(1.0, np.abs(want).max())


def test_degenerate_self_only_horizon():
    # horizon smaller than any inter-point distance: only self edges,
    # m2 == 0 -> c == 0 -> L == 0 identically
    pts = np.stack([np.linspace(0, 1, 40), np.zeros(40)], axis=1)
    op = UnstructuredNonlocalOp(pts, 1e-6, k=1.0, dt=1e-6)
    u = np.random.default_rng(5).normal(size=op.n)
    got = np.asarray(op.apply(jnp.asarray(u), layout="windowed"))
    assert np.max(np.abs(got)) == 0.0


def test_solver_windowed_holds_manufactured_contract():
    op = _cloud(24)
    s = UnstructuredSolver(op, nt=25, backend="jit", layout="windowed")
    s.test_init()
    s.do_work()
    assert s.error_l2 / op.n <= 1e-6


def test_solver_windowed_matches_edges_trajectory():
    op = _cloud(24)
    runs = {}
    for layout in ("edges", "windowed"):
        s = UnstructuredSolver(op, nt=20, backend="jit", layout=layout)
        s.test_init()
        runs[layout] = np.asarray(s.do_work())
    scale = max(1.0, np.abs(runs["edges"]).max())
    assert np.max(np.abs(runs["windowed"] - runs["edges"])) < 1e-11 * scale


def test_solver_windowed_checkpoint_state_is_original_order(tmp_path):
    from nonlocalheatequation_tpu.utils.checkpoint import load_state

    op = _cloud(24)
    path = str(tmp_path / "ck.npz")
    s = UnstructuredSolver(op, nt=20, backend="jit", layout="windowed",
                           checkpoint_path=path, ncheckpoint=10)
    s.test_init()
    u_final = np.asarray(s.do_work())
    state, t_next, _ = load_state(path)
    # the checkpoint at t=20 must equal the final state in ORIGINAL order
    assert t_next == 20
    assert np.max(np.abs(np.asarray(state) - u_final)) == 0.0

    # and a resumed run from the mid checkpoint must land on the same
    # trajectory as an uninterrupted edges-layout run
    ref = UnstructuredSolver(op, nt=20, backend="jit", layout="edges")
    ref.test_init()
    u_ref = np.asarray(ref.do_work())
    assert np.max(np.abs(u_final - u_ref)) < 1e-11 * max(1.0, np.abs(u_ref).max())


# ---------------------------------------------------------------------------
# Offset (DIA) layout
# ---------------------------------------------------------------------------


def _offset_plan_of(op, **kw):
    from nonlocalheatequation_tpu.ops.windowed import build_offset_plan

    return build_offset_plan(op.tgt, op.src, op.edge_w, op.c, op.wsum,
                             op.n, **kw)


def test_offsets_matches_oracle_on_jittered_grid():
    op = _cloud(48)
    plan = _offset_plan_of(op)
    # a jittered grid in natural order must land entirely on raster offsets
    assert plan.coverage == 1.0
    assert plan.ov_tgt.size == 0
    u = np.random.default_rng(6).normal(size=op.n)
    got = np.asarray(op.apply(jnp.asarray(u), layout="offsets"))
    want = op.apply_np(u)
    assert np.max(np.abs(got - want)) < 1e-12 * max(1.0, np.abs(want).max())


def test_offsets_residual_path_stays_exact():
    op = _cloud(32)
    plan = _offset_plan_of(op, max_offsets=8)  # force most edges residual
    assert plan.ov_tgt.size > 0
    u = np.random.default_rng(7).normal(size=op.n)
    got = np.asarray(plan.for_dtype(jnp.float64).L(jnp.asarray(u)))
    want = op.apply_np(u)
    assert np.max(np.abs(got - want)) < 1e-12 * max(1.0, np.abs(want).max())


def test_offsets_on_irregular_cloud_is_exact_but_uncovered():
    rng = np.random.default_rng(8)
    pts = rng.uniform(size=(800, 2))  # no grid structure at all
    op = UnstructuredNonlocalOp(pts, 0.09, k=1.0, dt=1e-6, vol=1.25e-3)
    plan = _offset_plan_of(op, max_offsets=64)
    assert plan.coverage < 0.9  # detection honestly reports the mismatch
    u = rng.normal(size=op.n)
    got = np.asarray(plan.for_dtype(jnp.float64).L(jnp.asarray(u)))
    want = op.apply_np(u)
    assert np.max(np.abs(got - want)) < 1e-12 * max(1.0, np.abs(want).max())


def test_offsets_accounts_for_every_edge():
    op = _cloud(32)
    plan = _offset_plan_of(op)
    in_diag = int((np.asarray(plan.W) != 0).sum())
    assert in_diag + plan.ov_tgt.size == len(op.tgt)


def test_solver_offsets_holds_manufactured_contract():
    op = _cloud(24)
    s = UnstructuredSolver(op, nt=25, backend="jit", layout="offsets")
    s.test_init()
    s.do_work()
    assert s.error_l2 / op.n <= 1e-6


def test_solver_offsets_matches_edges_trajectory():
    op = _cloud(24)
    runs = {}
    for layout in ("edges", "offsets"):
        s = UnstructuredSolver(op, nt=20, backend="jit", layout=layout)
        s.test_init()
        runs[layout] = np.asarray(s.do_work())
    scale = max(1.0, np.abs(runs["edges"]).max())
    assert np.max(np.abs(runs["offsets"] - runs["edges"])) < 1e-11 * scale


def test_choose_layout_policy(monkeypatch):
    op = _cloud(24)
    # off-TPU: the device-side fast paths must not engage implicitly
    assert op.choose_layout() in ("ell", "edges")
    monkeypatch.setenv("NLHEAT_OFFSETS", "1")
    assert op.choose_layout() == "offsets"
    monkeypatch.setenv("NLHEAT_OFFSETS", "0")
    monkeypatch.setenv("NLHEAT_WINDOWED", "1")
    assert op.choose_layout() == "windowed"


def test_offset_stats_matches_plan_without_materializing():
    from nonlocalheatequation_tpu.ops.windowed import offset_stats

    op = _cloud(32)
    cov, keep_n, w_bytes = offset_stats(op.tgt, op.src, op.n)
    plan = _offset_plan_of(op)
    assert cov == pytest.approx(plan.coverage)
    assert keep_n == len(plan.offs)
    assert w_bytes == plan.w_bytes_f32


def test_plan_stats_matches_plan_without_materializing():
    # ADVICE r4: the windowed worthwhileness gate must judge coverage and
    # strip bytes without allocating the dense strips; the stats must agree
    # with what build_plan actually produces
    from nonlocalheatequation_tpu.ops.windowed import plan_stats

    op = _cloud(32)
    cov, p_bytes = plan_stats(op.points, op.eps, op.tgt, op.src)
    plan = _plan_of(op)
    assert cov == pytest.approx(plan.coverage)
    assert p_bytes == plan.p_bytes_f32


def test_morton_perm_and_plan_on_empty_cloud():
    # ADVICE r4: morton_perm did pts.min() on a zero-size array
    perm = morton_perm(np.zeros((0, 2)), 1.0)
    assert perm.shape == (0,)
    z = np.zeros(0)
    plan = build_plan(np.zeros((0, 2)), z, np.zeros(0, np.int64),
                      np.zeros(0, np.int64), z, z, z)
    assert plan.n == 0 and plan.coverage == 1.0


def test_offset_plan_duplicate_edges_accumulate():
    # ADVICE r4: build_edges never produces duplicate (tgt, src) pairs, but
    # a caller handing them in must get accumulation, not silent dropping
    from nonlocalheatequation_tpu.ops.windowed import build_offset_plan

    tgt = np.array([0, 1, 2, 0], np.int64)
    src = np.array([1, 2, 3, 1], np.int64)
    w = np.array([1.0, 2.0, 3.0, 4.0])
    n = 4
    plan = build_offset_plan(tgt, src, w, np.ones(n), np.ones(n), n)
    u = np.array([1.0, 10.0, 100.0, 1000.0])
    got = np.asarray(plan.for_dtype(jnp.float64).neighbor_sum(jnp.asarray(u)))
    want = np.zeros(n)
    np.add.at(want, tgt, w * u[src])
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-12)


def test_plan_cache_rebuilds_on_different_kwargs():
    op = _cloud(32)
    full = op.offset_plan()
    small = op.offset_plan(max_offsets=8)
    assert len(small.offs) == 8 < len(full.offs)
    wfull = op.windowed_plan()
    wsmall = op.windowed_plan(wmax=128)
    assert wsmall.W == 128 <= wfull.W


def test_solver_explicit_layout_on_sharded_op_falls_back(monkeypatch):
    import jax
    from nonlocalheatequation_tpu.ops.unstructured import ShardedUnstructuredOp

    op = _cloud(16)
    sh = ShardedUnstructuredOp(op, devices=jax.devices("cpu")[:2])
    s = UnstructuredSolver(sh, nt=5, backend="jit", layout="ell")
    s.test_init()
    s.do_work()  # must not TypeError; layout silently ignored for sharded
    assert s.error_l2 / op.n <= 1e-6


# ---------------------------------------------------------------------------
# Sharded offsets layout (gather-free multichip unstructured path)
# ---------------------------------------------------------------------------


def test_sharded_offsets_matches_oracle_and_single_device():
    import jax
    from nonlocalheatequation_tpu.ops.unstructured import ShardedUnstructuredOp

    op = _cloud(32)
    sh = ShardedUnstructuredOp(op, devices=jax.devices("cpu")[:4])
    assert sh.layout == "offsets"  # jittered grid: full coverage, auto picks
    u = np.random.default_rng(9).normal(size=op.n)
    got = np.asarray(sh.apply(jnp.asarray(u)))
    want = op.apply_np(u)
    scale = max(1.0, np.abs(want).max())
    assert np.max(np.abs(got - want)) < 1e-12 * scale
    single = np.asarray(op.apply(jnp.asarray(u), layout="offsets"))
    assert np.max(np.abs(got - single)) < 1e-12 * scale


def test_sharded_offsets_n_not_divisible_by_devices():
    import jax
    from nonlocalheatequation_tpu.ops.unstructured import ShardedUnstructuredOp

    rng = np.random.default_rng(10)
    h = 1.0 / 30
    xs, ys = np.meshgrid(np.arange(30) * h, np.arange(30) * h, indexing="ij")
    pts = np.stack([xs.ravel(), ys.ravel()], axis=1)  # 900 nodes, 8 devices
    pts += rng.uniform(-0.2 * h, 0.2 * h, pts.shape)
    op = UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-6, vol=h * h)
    sh = ShardedUnstructuredOp(op, devices=jax.devices("cpu"))
    assert sh.layout == "offsets"
    u = rng.normal(size=op.n)
    got = np.asarray(sh.apply(jnp.asarray(u)))
    want = op.apply_np(u)
    assert np.max(np.abs(got - want)) < 1e-12 * max(1.0, np.abs(want).max())


def test_sharded_offsets_explicit_on_irregular_cloud_raises():
    import jax
    from nonlocalheatequation_tpu.ops.unstructured import ShardedUnstructuredOp

    rng = np.random.default_rng(11)
    pts = rng.uniform(size=(600, 2))
    op = UnstructuredNonlocalOp(pts, 0.09, k=1.0, dt=1e-6, vol=1.7e-3)
    with pytest.raises(ValueError, match="offsets"):
        ShardedUnstructuredOp(op, devices=jax.devices("cpu")[:4],
                              layout="offsets")
    # auto falls back to the edge layout silently
    sh = ShardedUnstructuredOp(op, devices=jax.devices("cpu")[:4])
    assert sh.layout == "edges"
    u = rng.normal(size=op.n)
    got = np.asarray(sh.apply(jnp.asarray(u)))
    want = op.apply_np(u)
    assert np.max(np.abs(got - want)) < 1e-12 * max(1.0, np.abs(want).max())


def test_sharded_explicit_halo_keeps_edge_layout():
    import jax
    from nonlocalheatequation_tpu.ops.unstructured import ShardedUnstructuredOp

    op = _cloud(24)  # quasi-grid: offsets WOULD fit, but halo is explicit
    sh = ShardedUnstructuredOp(op, devices=jax.devices("cpu")[:4],
                               halo="export")
    assert sh.layout == "edges"
    assert sh.halo_mode == "export"


def test_sharded_offsets_solver_contract():
    import jax
    from nonlocalheatequation_tpu.ops.unstructured import ShardedUnstructuredOp

    op = _cloud(24)
    sh = ShardedUnstructuredOp(op, devices=jax.devices("cpu")[:4])
    assert sh.layout == "offsets"
    s = UnstructuredSolver(sh, nt=20, backend="jit")
    s.test_init()
    s.do_work()
    assert s.error_l2 / op.n <= 1e-6


def test_layouts_agree_with_influence_and_variable_vol():
    # J != 1 and non-uniform volumes: every layout must carry the same
    # per-edge weights (the DIA/window planners only re-ARRANGE edge_w)
    rng = np.random.default_rng(12)
    m = 24
    h = 1.0 / m
    xs, ys = np.meshgrid(np.arange(m) * h, np.arange(m) * h, indexing="ij")
    pts = np.stack([xs.ravel(), ys.ravel()], axis=1)
    pts += rng.uniform(-0.2 * h, 0.2 * h, pts.shape)
    vol = h * h * rng.uniform(0.5, 1.5, size=len(pts))
    op = UnstructuredNonlocalOp(pts, 3.0 * h, k=1.0, dt=1e-6, vol=vol,
                                influence=lambda r: 1.0 - 0.5 * r)
    u = rng.normal(size=op.n)
    want = op.apply_np(u)
    scale = max(1.0, np.abs(want).max())
    for layout in ("offsets", "windowed", "ell", "edges"):
        got = np.asarray(op.apply(jnp.asarray(u), layout=layout))
        assert np.max(np.abs(got - want)) < 1e-12 * scale, layout


def test_layouts_on_1d_cloud():
    rng = np.random.default_rng(13)
    n = 300
    pts = (np.arange(n) / n + rng.uniform(-2e-4, 2e-4, n)).reshape(n, 1)
    op = UnstructuredNonlocalOp(pts, 4.0 / n, k=1.0, dt=1e-7, vol=1.0 / n)
    u = rng.normal(size=n)
    want = op.apply_np(u)
    scale = max(1.0, np.abs(want).max())
    for layout in ("offsets", "windowed", "edges"):
        got = np.asarray(op.apply(jnp.asarray(u), layout=layout))
        assert np.max(np.abs(got - want)) < 1e-12 * scale, layout


def test_two_windows_beat_one_on_shuffled_clouds():
    # quadrant jumps in the Morton curve split a block's sources into a
    # few clusters; two windows must reach the same coverage with less
    # total strip width than one window (the 2.7x traffic cut the
    # fallback path banks on)
    rng = np.random.default_rng(14)
    m = 48
    h = 1.0 / m
    xs, ys = np.meshgrid(np.arange(m) * h, np.arange(m) * h, indexing="ij")
    pts = np.stack([xs.ravel(), ys.ravel()], axis=1)
    pts += rng.uniform(-0.2 * h, 0.2 * h, pts.shape)
    shuf = rng.permutation(m * m)
    op = UnstructuredNonlocalOp(pts[shuf], 3.0 * h, k=1.0, dt=1e-6,
                                vol=h * h)
    two = _plan_of(op, windows=2)
    one = _plan_of(op, windows=1)
    assert two.R == 2 and one.R == 1
    assert two.W <= one.W
    assert two.coverage >= one.coverage - 1e-12
    # and both exact
    u = rng.normal(size=op.n)
    want = op.apply_np(u)
    scale = max(1.0, np.abs(want).max())
    for plan in (one, two):
        got = np.asarray(plan.for_dtype(jnp.float64).L(jnp.asarray(u)))
        assert np.max(np.abs(got - want)) < 1e-12 * scale
