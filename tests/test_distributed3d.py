"""3D distributed solver on the 8-virtual-device CPU mesh.

Same structural checks the 2D distributed suite applies (multi==single
device, ==serial oracle, multi-hop halos when eps exceeds the shard edge),
one rank up — these are the framework's determinism/race-freedom analogs
(SURVEY.md section 5).
"""

import numpy as np

from nonlocalheatequation_tpu.models.solver3d import Solver3D
from nonlocalheatequation_tpu.parallel.distributed3d import (
    Solver3DDistributed,
    choose_mesh_for_grid_3d,
)
from nonlocalheatequation_tpu.parallel.mesh import make_mesh_3d


def test_3d_distributed_manufactured_contract():
    s = Solver3DDistributed(16, 16, 16, nt=20, eps=2, k=0.5, dt=0.0005, dh=0.05,
                            mesh=make_mesh_3d(2, 2, 2))
    s.test_init()
    s.do_work()
    assert s.error_l2 / 16**3 <= 1e-6
    assert s.mesh.devices.size == 8


def test_3d_multi_device_equals_single_device():
    kw = dict(nt=10, eps=2, k=0.5, dt=0.0005, dh=0.05)
    a = Solver3DDistributed(12, 12, 12, mesh=make_mesh_3d(1, 1, 1), **kw)
    b = Solver3DDistributed(12, 12, 12, mesh=make_mesh_3d(2, 2, 2), **kw)
    a.test_init()
    b.test_init()
    ua, ub = a.do_work(), b.do_work()
    assert np.abs(ua - ub).max() < 1e-12


def test_3d_distributed_equals_serial_oracle():
    o = Solver3D(12, 12, 12, nt=10, eps=2, k=0.5, dt=0.0005, dh=0.05,
                 backend="oracle")
    d = Solver3DDistributed(12, 12, 12, nt=10, eps=2, k=0.5, dt=0.0005, dh=0.05,
                            mesh=make_mesh_3d(2, 2, 2))
    o.test_init()
    d.test_init()
    uo, ud = o.do_work(), d.do_work()
    assert np.abs(uo - ud).max() < 1e-12


def test_3d_multihop_halo_when_eps_exceeds_shard():
    # 12^3 on a (4,2,1) mesh -> x shard edge 3; eps=4 needs 2 hops in x
    o = Solver3D(12, 12, 12, nt=8, eps=4, k=0.2, dt=0.0005, dh=0.05,
                 backend="oracle")
    d = Solver3DDistributed(12, 12, 12, nt=8, eps=4, k=0.2, dt=0.0005, dh=0.05,
                            mesh=make_mesh_3d(4, 2, 1))
    o.test_init()
    d.test_init()
    uo, ud = o.do_work(), d.do_work()
    assert np.abs(uo - ud).max() < 1e-12


def test_3d_pallas_inside_shard_map():
    # the 3D strip kernel runs under shard_map (interpreter off-TPU)
    kw = dict(nt=3, eps=2, k=0.5, dt=0.0005, dh=0.05)
    a = Solver3DDistributed(16, 16, 16, method="shift",
                            mesh=make_mesh_3d(2, 2, 2), **kw)
    b = Solver3DDistributed(16, 16, 16, method="pallas",
                            mesh=make_mesh_3d(2, 2, 2), **kw)
    a.test_init()
    b.test_init()
    ua, ub = a.do_work(), b.do_work()
    assert np.abs(ua - ub).max() < 1e-10


def test_3d_choose_mesh_divides_grid():
    mesh = choose_mesh_for_grid_3d(16, 16, 16)
    mx, my, mz = mesh.shape["x"], mesh.shape["y"], mesh.shape["z"]
    assert 16 % mx == 0 and 16 % my == 0 and 16 % mz == 0
    assert mx * my * mz == 8


def test_3d_free_decay_distributed_matches_oracle():
    rng = np.random.default_rng(5)
    u0 = rng.normal(size=(12, 12, 12))
    o = Solver3D(12, 12, 12, nt=8, eps=2, k=0.5, dt=0.001, dh=0.05,
                 backend="oracle")
    d = Solver3DDistributed(12, 12, 12, nt=8, eps=2, k=0.5, dt=0.001, dh=0.05,
                            mesh=make_mesh_3d(2, 2, 2))
    o.input_init(u0)
    d.input_init(u0)
    uo, ud = o.do_work(), d.do_work()
    assert np.abs(uo - ud).max() < 1e-12


def test_3d_superstep_equals_per_step_and_oracle():
    """Communication-avoiding superstep in 3D: one K*eps-wide exchange per
    K steps (multi-hop across the 2-wide shards), remainder included;
    matches the per-step path and the serial oracle <=1e-12."""
    kw = dict(nt=7, eps=2, k=0.5, dt=0.0005, dh=0.05,
              mesh=make_mesh_3d(2, 2, 2))
    a = Solver3DDistributed(12, 12, 12, **kw)
    b = Solver3DDistributed(12, 12, 12, superstep=3, **kw)
    o = Solver3D(12, 12, 12, nt=7, eps=2, k=0.5, dt=0.0005, dh=0.05,
                 backend="oracle")
    for s in (a, b, o):
        s.test_init()
    ua, ub, uo = a.do_work(), b.do_work(), o.do_work()
    assert np.abs(ua - ub).max() < 1e-12
    assert np.abs(uo - ub).max() < 1e-12
