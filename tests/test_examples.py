"""The examples/ scripts must stay runnable (they are documentation)."""

import glob
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(glob.glob(os.path.join(REPO, "examples", "*.py")))


@pytest.mark.parametrize("script", EXAMPLES, ids=[os.path.basename(e) for e in EXAMPLES])
def test_example_runs(script, tmp_path):
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split() if "device_count" not in f]
    env["XLA_FLAGS"] = " ".join(flags + ["--xla_force_host_platform_device_count=8"])
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, "--platform", "cpu"],
        capture_output=True, text=True, timeout=420, cwd=str(tmp_path), env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
