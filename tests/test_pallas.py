"""Pallas horizon kernel: equality vs the shift oracle path + full solves.

Runs in Pallas interpreter mode on the CPU test backend (f64), exercising the
same kernel code the TPU compiles (ops/pallas_kernel.py).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from tests.cases import CASES_2D, L2_THRESHOLD

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.ops.nonlocal_op import NonlocalOp2D, make_step_fn
from nonlocalheatequation_tpu.ops.pallas_kernel import _naf, _strip_plan
from nonlocalheatequation_tpu.ops.stencil import horizon_mask_2d

SHAPES = [
    (64, 64, 8),     # aligned, bench-like
    (50, 37, 5),     # ragged both axes
    (100, 128, 10),
    (16, 16, 3),
    (10, 10, 12),    # eps > grid (the reference's nx <= eps degenerate case)
    (24, 24, 1),     # smallest stencil
]


@pytest.mark.parametrize("nx,ny,eps", SHAPES)
def test_neighbor_sum_matches_shift(nx, ny, eps):
    rng = np.random.default_rng(nx * 1000 + ny + eps)
    u = jnp.asarray(rng.normal(size=(nx, ny)))
    a = NonlocalOp2D(eps, 1.0, 1e-4, 0.01, method="shift").neighbor_sum(u)
    b = NonlocalOp2D(eps, 1.0, 1e-4, 0.01, method="pallas").neighbor_sum(u)
    assert float(jnp.max(jnp.abs(a - b))) < 1e-10


@pytest.mark.parametrize("nx,ny,eps", SHAPES[:3])
def test_fused_step_matches_reference_step(nx, ny, eps):
    rng = np.random.default_rng(7)
    u = jnp.asarray(rng.normal(size=(nx, ny)))
    op_s = NonlocalOp2D(eps, 1.0, 1e-4, 0.01, method="shift")
    op_p = NonlocalOp2D(eps, 1.0, 1e-4, 0.01, method="pallas")
    g, lg = op_s.source_parts(nx, ny)
    for t in (0, 3):
        a = make_step_fn(op_s, g, lg)(u, t)
        b = make_step_fn(op_p, g, lg)(u, t)
        assert float(jnp.max(jnp.abs(a - b))) < 1e-10


def test_batch_case_pallas_backend():
    nx, ny, nt, eps, k, dt, dh = CASES_2D[0]
    s = Solver2D(nx, ny, nt, eps, k=k, dt=dt, dh=dh, backend="jit", method="pallas")
    s.test_init()
    s.do_work()
    assert s.error_l2 / (nx * ny) <= L2_THRESHOLD


def test_3d_solver_pallas_contract():
    from nonlocalheatequation_tpu.models.solver3d import Solver3D

    s = Solver3D(16, 16, 16, nt=15, eps=2, k=0.5, dt=0.0005, dh=0.05,
                 backend="jit", method="pallas")
    s.test_init()
    s.do_work()
    assert s.error_l2 / 16**3 <= L2_THRESHOLD


def test_distributed_pallas_matches_shift():
    """method='pallas' inside shard_map (vma propagation + check_vma

    workaround), one-hop and multi-hop halo cases."""
    import numpy as np

    from nonlocalheatequation_tpu.parallel.distributed2d import Solver2DDistributed
    from nonlocalheatequation_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(2, 4)
    for eps, nt, dt in [(2, 3, 1e-4), (9, 2, 1e-5)]:  # eps=9 > shard edge
        a = Solver2DDistributed(16, 8, 2, 4, nt=nt, eps=eps, k=1.0, dt=dt,
                                dh=0.03125, mesh=mesh, method="pallas")
        a.test_init(); a.do_work()
        b = Solver2DDistributed(16, 8, 2, 4, nt=nt, eps=eps, k=1.0, dt=dt,
                                dh=0.03125, mesh=mesh, method="shift")
        b.test_init(); b.do_work()
        assert np.abs(a.u - b.u).max() < 1e-12


def test_3d_block_dims_satisfy_mosaic_constraints(monkeypatch):
    """Mosaic (real-TPU) lowering requires the last-two block dims be
    (multiple of 8, multiple of 128) or equal the array dims.  The 3D
    kernel's y window must therefore be widened to a multiple of 8 for ANY
    eps — found on hardware in round 3 (128^3 eps=6 failed to lower while
    interpreter-mode CI accepted it).  Regression: spy on the BlockSpecs
    the kernel ACTUALLY emits (the interpreter itself cannot validate the
    constraint, so inspect what a real TPU would be handed)."""
    from nonlocalheatequation_tpu.ops import pallas_kernel as pk

    recorded = {}
    real_call = pk.pl.pallas_call

    def spy(kernel, **kw):
        recorded["in_specs"] = kw["in_specs"]
        recorded["out_shape"] = kw["out_shape"]
        return real_call(kernel, **kw)

    monkeypatch.setattr(pk.pl, "pallas_call", spy)
    for eps, n in [(6, 24), (3, 32), (5, 16), (4, 32)]:
        pk.build_neighbor_sum_3d.cache_clear()
        fn = pk.build_neighbor_sum_3d(eps, n, n, n, "float64")
        upad = jnp.zeros((n + 2 * eps,) * 3)
        fn(upad)
        blk = recorded["in_specs"][0].block_shape
        mid = getattr(blk[1], "block_size", blk[1])
        last = getattr(blk[2], "block_size", blk[2])
        assert mid % 8 == 0, (eps, n, mid)  # the round-3 hardware bug
        assert last == n + 2 * eps  # z block == full padded axis
    pk.build_neighbor_sum_3d.cache_clear()


def test_auto_method_resolution():
    """method='auto' picks per backend/dtype/shape and NEVER raises for
    infeasible shapes (review finding r3: auto must not crash where the
    old explicit defaults worked)."""
    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        _auto_method_2d,
        _auto_method_3d,
    )

    f32, f64 = jnp.dtype("float32"), jnp.dtype("float64")
    assert _auto_method_2d(8, 512, 512, f32, backend="cpu") == "conv"
    assert _auto_method_2d(8, 512, 512, f32, backend="tpu") == "pallas"
    # Mosaic is f32-only -> the f64-capable sat path
    assert _auto_method_2d(8, 512, 512, f64, backend="tpu") == "sat"
    # a row too wide for the kernel's VMEM budget falls back, no ValueError
    assert _auto_method_2d(8, 512, 3_000_000, f32, backend="tpu") == "sat"
    assert _auto_method_3d(4, 64, 64, 64, f32, backend="cpu") == "sat"
    assert _auto_method_3d(4, 64, 64, 64, f32, backend="tpu") == "pallas"
    assert _auto_method_3d(4, 64, 64, 64, f64, backend="tpu") == "sat"
    assert _auto_method_3d(6, 64, 64, 3_000_000, f32, backend="tpu") == "sat"


def test_auto_method_end_to_end_solve():
    # an op constructed with method='auto' solves the manufactured problem
    # identically to whatever explicit method it resolves to on THIS backend
    # (bitwise comparison stays valid on TPU, where auto picks pallas)
    import jax as _jax

    from nonlocalheatequation_tpu.models.solver2d import Solver2D
    from nonlocalheatequation_tpu.ops.nonlocal_op import _auto_method_2d

    expected = _auto_method_2d(5, 50, 50, jnp.dtype(np.float64)
                               if _jax.config.jax_enable_x64
                               else jnp.dtype(np.float32))
    a = Solver2D(50, 50, 30, eps=5, k=1.0, dt=0.0005, dh=0.02,
                 backend="jit", method="auto")
    b = Solver2D(50, 50, 30, eps=5, k=1.0, dt=0.0005, dh=0.02,
                 backend="jit", method=expected)
    a.test_init()
    b.test_init()
    ua, ub = a.do_work(), b.do_work()
    assert np.array_equal(ua, ub)
    assert a.error_l2 / 2500 <= 1e-6


def test_carried_multi_step_bit_identical():
    """The carried-frame multi-step kernel (bench fast path) must be
    BIT-identical to the per-step pad+kernel path: same plan, same
    summation order, only frame bookkeeping differs."""
    import jax.numpy as jnp

    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        NonlocalOp2D,
        make_multi_step_fn_base as make_multi_step_fn,
    )
    from nonlocalheatequation_tpu.ops.pallas_kernel import (
        make_carried_multi_step_fn,
    )

    rng = np.random.default_rng(3)
    for n, eps, steps in [(64, 5, 4), (40, 3, 3), (48, 12, 2)]:
        op = NonlocalOp2D(eps, k=1.0, dt=1e-6, dh=1.0 / n, method="pallas")
        ref = make_multi_step_fn(op, steps, dtype=jnp.float32)
        new = make_carried_multi_step_fn(op, steps, dtype=jnp.float32)
        u = jnp.asarray(rng.normal(size=(n, n)), jnp.float32)
        a = np.asarray(ref(u, jnp.int32(0)))
        b = np.asarray(new(u, jnp.int32(0)))
        assert np.array_equal(a, b), (n, eps, np.abs(a - b).max())


def test_superstep_multi_step_bit_identical():
    """The K-step temporally blocked kernel (temporal blocking of the
    copy-floor-bound headline kernel) must be BIT-identical to the
    per-step pad+kernel path: each level runs the same plan and the same
    update expression, and an optimization barrier between levels pins
    the per-step path's fusion context (see _build_superstep_kernel).
    Covers remainders (nsteps % K != 0), K > 2, eps spanning the lane-run
    classes, a non-multiple-of-8 grid, and a chained smoothed state (the
    case that exposed the fusion-boundary ulp flip)."""
    import jax.numpy as jnp

    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        NonlocalOp2D,
        make_multi_step_fn_base as make_multi_step_fn,
    )
    from nonlocalheatequation_tpu.ops.pallas_kernel import (
        make_superstep_multi_step_fn,
    )

    rng = np.random.default_rng(11)
    for n, eps, steps, K in [(64, 5, 5, 2), (40, 3, 6, 3), (48, 12, 2, 2),
                             (56, 7, 4, 4), (33, 4, 4, 2), (40, 1, 5, 2),
                             (64, 16, 4, 2)]:
        op = NonlocalOp2D(eps, k=1.0, dt=1e-6, dh=1.0 / n, method="pallas")
        ref = make_multi_step_fn(op, steps, dtype=jnp.float32)
        new = make_superstep_multi_step_fn(op, steps, ksteps=K,
                                           dtype=jnp.float32)
        u = jnp.asarray(rng.normal(size=(n, n)), jnp.float32)
        # the fusion-boundary flips only surfaced on smoothed states:
        # compare from a few-steps-evolved field, not just raw noise
        v = ref(u, jnp.int32(0))
        for w in (u, v):
            a = np.asarray(ref(w, jnp.int32(0)))
            b = np.asarray(new(w, jnp.int32(0)))
            assert np.array_equal(a, b), (n, eps, steps, K,
                                          np.abs(a - b).max())


def test_superstep_production_dispatch(monkeypatch):
    """NLHEAT_SUPERSTEP=K upgrades make_multi_step_fn's production 2D
    pallas path to the temporally blocked kernel, bit-identically.  The
    superstep is bit-identical BY CONTRACT, so equality alone cannot
    detect a dispatch regression — spy on the maker to pin that the
    branch actually fires, and that resident wins when both knobs are
    set and the grid fits residency."""
    import jax.numpy as jnp

    import nonlocalheatequation_tpu.ops.pallas_kernel as pk
    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        NonlocalOp2D,
        make_multi_step_fn,
        make_multi_step_fn_base,
    )

    calls = []
    real_sup = pk.make_superstep_multi_step_fn
    real_res = pk.make_resident_multi_step_fn
    monkeypatch.setattr(
        pk, "make_superstep_multi_step_fn",
        lambda *a, **kw: calls.append("superstep") or real_sup(*a, **kw))
    monkeypatch.setattr(
        pk, "make_resident_multi_step_fn",
        lambda *a, **kw: calls.append("resident") or real_res(*a, **kw))

    op = NonlocalOp2D(5, k=1.0, dt=1e-6, dh=1.0 / 64, method="pallas")
    u = jnp.asarray(np.random.default_rng(2).normal(size=(64, 64)),
                    jnp.float32)
    ref = make_multi_step_fn_base(op, 5, dtype=jnp.float32)(u, jnp.int32(0))
    monkeypatch.setenv("NLHEAT_SUPERSTEP", "2")
    got = make_multi_step_fn(op, 5, dtype=jnp.float32)(u, jnp.int32(0))
    assert calls == ["superstep"]
    assert np.array_equal(np.asarray(ref), np.asarray(got))
    # resident wins when both knobs are set and the grid fits residency
    monkeypatch.setenv("NLHEAT_RESIDENT", "1")
    both = make_multi_step_fn(op, 5, dtype=jnp.float32)(u, jnp.int32(0))
    assert calls == ["superstep", "resident"]
    assert np.array_equal(np.asarray(ref), np.asarray(both))


def test_carried_multi_step_3d_bit_identical():
    """3D carried-frame multi-step kernel: bit-identical to the per-step
    pad+kernel path (same plan, same summation order)."""
    import jax.numpy as jnp

    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        NonlocalOp3D,
        make_multi_step_fn_base as make_multi_step_fn,
    )
    from nonlocalheatequation_tpu.ops.pallas_kernel import (
        make_carried_multi_step_fn_3d,
    )

    rng = np.random.default_rng(5)
    for n, eps, steps in [(32, 4, 3), (24, 6, 2), (40, 3, 2)]:
        op = NonlocalOp3D(eps, k=1.0, dt=1e-7, dh=1.0 / n, method="pallas")
        ref = make_multi_step_fn(op, steps, dtype=jnp.float32)
        new = make_carried_multi_step_fn_3d(op, steps, dtype=jnp.float32)
        u = jnp.asarray(rng.normal(size=(n, n, n)), jnp.float32)
        a = np.asarray(ref(u, jnp.int32(0)))
        b = np.asarray(new(u, jnp.int32(0)))
        assert np.array_equal(a, b), (n, eps, np.abs(a - b).max())


def test_resident_multi_step_bit_identical():
    """The VMEM-resident whole-run kernel (one pallas_call for all steps,
    state ping-ponging between two scratch frames) must be BIT-identical
    to the per-step pad+kernel path: _strip_neighbor_sum over the full
    frame in one strip sums the same slices in the same order as the
    strip-partitioned form.  Covers odd/even step counts and steps=1."""
    import jax.numpy as jnp

    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        NonlocalOp2D,
        make_multi_step_fn_base as make_multi_step_fn,
    )
    from nonlocalheatequation_tpu.ops.pallas_kernel import (
        fits_resident,
        make_resident_multi_step_fn,
    )

    rng = np.random.default_rng(4)
    for n, eps, steps in [(64, 5, 5), (40, 3, 4), (48, 12, 1), (128, 8, 2)]:
        assert fits_resident(n, n, eps)
        op = NonlocalOp2D(eps, k=1.0, dt=1e-6, dh=1.0 / n, method="pallas")
        ref = make_multi_step_fn(op, steps, dtype=jnp.float32)
        new = make_resident_multi_step_fn(op, steps, dtype=jnp.float32)
        u = jnp.asarray(rng.normal(size=(n, n)), jnp.float32)
        a = np.asarray(ref(u, jnp.int32(0)))
        b = np.asarray(new(u, jnp.int32(0)))
        assert np.array_equal(a, b), (n, eps, steps, np.abs(a - b).max())


def test_resident_rejects_overflowing_grid():
    """A grid past the VMEM budget must fail with the named error, not an
    opaque Mosaic allocation failure at compile time."""
    import jax.numpy as jnp

    from nonlocalheatequation_tpu.ops.nonlocal_op import NonlocalOp2D
    from nonlocalheatequation_tpu.ops.pallas_kernel import (
        fits_resident,
        make_resident_multi_step_fn,
    )

    assert not fits_resident(4096, 4096, 8)
    op = NonlocalOp2D(8, k=1.0, dt=1e-6, dh=1.0 / 4096, method="pallas")
    multi = make_resident_multi_step_fn(op, 2, dtype=jnp.float32)
    with pytest.raises(ValueError, match="resident kernel"):
        multi(jnp.zeros((4096, 4096), jnp.float32), jnp.int32(0))


def test_resident_multi_step_3d_bit_identical():
    """3D mirror of the resident whole-run kernel: bit-identical to the
    per-step path for grids that fit the (stricter) 3D VMEM model."""
    import jax.numpy as jnp

    from nonlocalheatequation_tpu.ops.nonlocal_op import (
        NonlocalOp3D,
        make_multi_step_fn_base,
    )
    from nonlocalheatequation_tpu.ops.pallas_kernel import (
        fits_resident_3d,
        make_resident_multi_step_fn_3d,
    )

    rng = np.random.default_rng(5)
    for n, eps, steps in [(32, 4, 5), (24, 3, 1), (48, 3, 2), (40, 4, 3)]:
        assert fits_resident_3d(n, n, n, eps)
        op = NonlocalOp3D(eps, k=1.0, dt=1e-7, dh=1.0 / n, method="pallas")
        ref = make_multi_step_fn_base(op, steps, dtype=jnp.float32)
        new = make_resident_multi_step_fn_3d(op, steps, dtype=jnp.float32)
        u = jnp.asarray(rng.normal(size=(n, n, n)), jnp.float32)
        a = np.asarray(ref(u, jnp.int32(0)))
        b = np.asarray(new(u, jnp.int32(0)))
        assert np.array_equal(a, b), (n, eps, steps, np.abs(a - b).max())
    # a config past the stricter 3D budget is refused with the named error
    assert not fits_resident_3d(64, 64, 64, 6)
    op = NonlocalOp3D(6, k=1.0, dt=1e-7, dh=1.0 / 64, method="pallas")
    multi = make_resident_multi_step_fn_3d(op, 2, dtype=jnp.float32)
    with pytest.raises(ValueError, match="resident 3D kernel"):
        multi(jnp.zeros((64, 64, 64), jnp.float32), jnp.int32(0))
