"""2D oracle: the reference's Test_2d batch cases (CMakeLists.txt:109-122)."""

import numpy as np
import pytest

from tests.cases import CASES_2D, L2_THRESHOLD

from nonlocalheatequation_tpu.models.solver2d import Solver2D
from nonlocalheatequation_tpu.ops.stencil import column_half_heights, horizon_mask_2d


@pytest.mark.parametrize("nx,ny,nt,eps,k,dt,dh", CASES_2D)
def test_batch_case_oracle(nx, ny, nt, eps, k, dt, dh):
    s = Solver2D(nx, ny, nt, eps, k=k, dt=dt, dh=dh, backend="oracle")
    s.test_init()
    s.do_work()
    assert s.error_l2 / (nx * ny) <= L2_THRESHOLD


def test_stencil_shape_matches_reference_raster():
    # len_1d_line truncation (src/2d_nonlocal_serial.cpp:231): eps=5 column
    # half-heights for offsets -5..5.
    assert list(column_half_heights(5)) == [0, 3, 4, 4, 4, 5, 4, 4, 4, 3, 0]
    m = horizon_mask_2d(5)
    assert m.shape == (11, 11)
    assert m[5, 5] and m[0, 5] and not m[0, 4]
    # symmetric under both reflections and transpose
    assert (m == m[::-1]).all() and (m == m[:, ::-1]).all() and (m == m.T).all()


def test_out_of_domain_counts_with_zero_value():
    # A point at the corner: out-of-domain stencil points contribute (0 - u),
    # i.e. the neighbor count does NOT shrink at the boundary
    # (boundary() returns 0, src/2d_nonlocal_serial.cpp:213-221).
    s = Solver2D(4, 4, 1, eps=3, k=1.0, dt=1e-4, dh=0.02, backend="oracle")
    u = np.ones((4, 4))
    out = s.op.apply_np(u)
    interiorish = s.op.c * s.op.dh**2
    # all stencil sums differ from wsum*u only via missing (zero) neighbors
    expected_corner = interiorish * (
        horizon_mask_2d(3)[3:, 3:].sum() - horizon_mask_2d(3).sum()
    )
    assert np.isclose(out[0, 0], expected_corner)
    assert out[0, 0] < 0  # ones field cools at the boundary collar


def test_multi_step_scan_matches_oracle():
    # make_multi_step_fn with NumPy (g, lg) inputs must trace cleanly and
    # match the oracle run (this is the bench/production fast path).
    from nonlocalheatequation_tpu.ops.nonlocal_op import make_multi_step_fn

    nx, ny, nt, eps, k, dt, dh = CASES_2D[0]
    s = Solver2D(nx, ny, nt, eps, k=k, dt=dt, dh=dh, backend="oracle")
    s.test_init()
    ref = s.do_work()

    g, lg = s.op.source_parts(nx, ny)
    multi = make_multi_step_fn(s.op, nt, g, lg)
    out = np.asarray(multi(s.op.spatial_profile(nx, ny), 0))
    assert abs(out - ref).max() < 1e-12
