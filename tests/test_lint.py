"""graftlint (tools/lint): per-rule fixtures, the suppression and
baseline machinery, the K1 engine-key completeness checker (including
the delete-one-dimension regression the PR-9 program store motivates),
the L1 lock-discipline checker, and the CLI end to end — which pins the
ISSUE 14 acceptance bar: ``python -m tools.lint`` exits 0 on this repo.
"""

import ast
import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.lint import enginekey, locks, rules  # noqa: E402
from tools.lint.core import (  # noqa: E402
    Suppressions,
    apply_baseline,
    load_baseline,
)

FIX = REPO / "tests" / "lint_fixtures"
ENSEMBLE = REPO / "nonlocalheatequation_tpu" / "serve" / "ensemble.py"
PICKER = REPO / "nonlocalheatequation_tpu" / "serve" / "picker.py"


def run_rule(rule: str, path: Path):
    """One rule over one fixture, suppressions honored — the same
    composition __main__.scan_file applies."""
    src = path.read_text()
    tree = ast.parse(src)
    found = rules.ALL_RULES[rule](str(path), src, tree, src.splitlines())
    sup = Suppressions(src)
    return [f for f in found if not sup.active(f.rule, f.line)]


# -- W/P rules against their fixtures ---------------------------------------


@pytest.mark.parametrize("rule,bad_hits", [
    ("W1", 3),  # devices() x2 forms + device_count()
    ("W2", 3),  # subscript write, setdefault, update
    ("W3", 2),  # f64-local scan + inline-f64 fori_loop
    ("W4", 1),
    ("P1", 1),
])
def test_rule_flags_bad_fixture(rule, bad_hits):
    found = run_rule(rule, FIX / f"{rule.lower()}_bad.py")
    assert len(found) == bad_hits, [f.render() for f in found]
    assert all(f.rule == rule for f in found)
    # every finding anchors to a real line of code for baseline matching
    assert all(f.code for f in found)


@pytest.mark.parametrize("rule", ["W1", "W2", "W3", "W4", "P1"])
def test_rule_passes_good_fixture(rule):
    found = run_rule(rule, FIX / f"{rule.lower()}_good.py")
    assert found == [], [f.render() for f in found]


def test_w4_suppression_requires_reason():
    src = (FIX / "w4_good.py").read_text().replace(
        "# lint-ok: W4 backpressure on the dispatch queue, not a "
        "timing fence",
        "# lint-ok: W4")
    sup = Suppressions(src)
    assert sup.unreasoned and sup.unreasoned[0][1] == "W4"
    # and the bare annotation no longer suppresses
    tree = ast.parse(src)
    found = rules.rule_w4("x.py", src, tree, src.splitlines())
    assert len(found) == 1
    assert not sup.active("W4", found[0].line)


# -- L1 lock discipline ------------------------------------------------------


def test_l1_flags_offlock_mutations():
    src = (FIX / "l1_bad.py").read_text()
    found = locks.check_locks("l1_bad.py", src, ast.parse(src))
    assert len(found) == 2, [f.render() for f in found]
    assert {"pop" in f.code or "+=" in f.code for f in found} == {True}
    assert all("on_reader_thread" in f.msg for f in found)


def test_l1_passes_good_fixture():
    src = (FIX / "l1_good.py").read_text()
    found = locks.check_locks("l1_good.py", src, ast.parse(src))
    assert found == [], [f.render() for f in found]


def test_l1_serve_tier_annotations_live():
    """The real serve tier declares guards (the annotations are not
    vestigial) and holds them — the dogfood state this PR establishes."""
    for relpath in ("nonlocalheatequation_tpu/serve/router.py",
                    "nonlocalheatequation_tpu/serve/transport.py"):
        src = (REPO / relpath).read_text()
        assert "guarded_by: self._lock" in src, relpath
        found = locks.check_locks(relpath, src, ast.parse(src))
        assert found == [], [f.render() for f in found]


# -- K1 engine-key completeness ---------------------------------------------


def test_k1_clean_on_repo():
    found = enginekey.check_engine_key(str(ENSEMBLE), str(PICKER))
    assert found == [], [f.render() for f in found]


@pytest.mark.parametrize("drop,expect", [
    # delete the stepper dimension from prog_key: two engines differing
    # only in integrator would share stored programs
    ("self.comm, self.stepper, self.stages)", "self.comm, self.stages)"),
    # delete the ksteps dimension from the store key: a superstep A/B
    # would serve the other arm's executable across processes
    ("self.method, self.precision,\n                                  "
     "self.ksteps))", "self.method, self.precision))"),
])
def test_k1_flags_deleted_key_dimension(tmp_path, drop, expect):
    src = ENSEMBLE.read_text()
    assert src.count(drop) == 1, (
        "key-builder text drifted — update this regression test AND "
        "check the K1 checker still resolves the new spelling")
    missing = "stepper" if "stepper" in drop else "ksteps"
    mutated = tmp_path / "ensemble_mutated.py"
    mutated.write_text(src.replace(drop, expect))
    found = enginekey.check_engine_key(str(mutated))
    assert any(f.rule == "K1" and f"'{missing}'" in f.msg
               for f in found), [f.render() for f in found]


def test_k1_flags_stale_allowlist_and_unknown_picker_axis(tmp_path):
    eng = tmp_path / "ensemble.py"
    eng.write_text(
        "class EnsembleEngine:\n"
        "    def __init__(self, method='auto'):\n"
        "        self.method = method\n"
        "    def build_program(self, key, chunk):\n"
        "        prog_key = (key, self.method)\n"
        "        return prog_key\n")
    found = enginekey.check_engine_key(str(eng))
    # every NONPROGRAM knob is stale against this minimal ctor
    stale = [f for f in found if "stale" in f.msg]
    assert len(stale) == len(enginekey.NONPROGRAM_KNOBS)
    pick = tmp_path / "picker.py"
    pick.write_text(
        "class EngineChoice:\n"
        "    def engine_kwargs(self):\n"
        "        return {'method': self.method, 'tile_w': self.tile_w}\n")
    found = enginekey.check_engine_key(str(ENSEMBLE), str(pick))
    assert any("tile_w" in f.msg for f in found), \
        [f.render() for f in found]


# -- baseline machinery ------------------------------------------------------


def test_baseline_split_and_staleness():
    from tools.lint.core import Finding

    f1 = Finding("W1", "a.py", 3, "m", code="jax.devices()")
    f2 = Finding("W1", "a.py", 9, "m", code="jax.devices()")
    f3 = Finding("W4", "b.py", 1, "m", code="x.block_until_ready()")
    entries = [
        {"rule": "W1", "path": "a.py", "code": "jax.devices()",
         "reason": "r"},
        {"rule": "W2", "path": "gone.py", "code": "os.environ[...]",
         "reason": "r"},
    ]
    split = apply_baseline([f1, f2, f3], entries)
    # one entry covers ONE of the two identical findings, by count
    assert [f.line for f in split.grandfathered] == [3]
    assert {(f.rule, f.line) for f in split.new} == {("W1", 9),
                                                    ("W4", 1)}
    assert [e["path"] for e in split.stale] == ["gone.py"]


def test_baseline_schema_refusals(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"rule": "W1"}))
    with pytest.raises(ValueError, match="JSON list"):
        load_baseline(p)
    p.write_text(json.dumps([{"rule": "W1", "path": "a.py"}]))
    with pytest.raises(ValueError, match="missing keys"):
        load_baseline(p)
    p.write_text(json.dumps([{"rule": "W1", "path": "a.py",
                              "code": "c", "reason": "  "}]))
    with pytest.raises(ValueError, match="empty reason"):
        load_baseline(p)


def test_shipped_baseline_is_reasoned():
    entries = load_baseline(REPO / "tools" / "lint" / "baseline.json")
    for e in entries:
        assert e["rule"] != "K1", "K1 findings may never be baselined"
        assert len(e["reason"]) > 20, e


# -- CLI end to end ----------------------------------------------------------


def _cli(*argv):
    return subprocess.run(
        [sys.executable, "-m", "tools.lint", *argv],
        capture_output=True, text=True, cwd=str(REPO), timeout=120)


def test_cli_repo_is_clean():
    """ISSUE 14 acceptance: the dogfooded repo lints clean modulo the
    explicit baseline, at rc 0."""
    proc = _cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


def test_cli_flags_fixture_at_rc1():
    proc = _cli("tests/lint_fixtures/w1_bad.py")
    assert proc.returncode == 1
    assert "W1" in proc.stdout


def test_cli_fix_rewrites_w1(tmp_path):
    target = tmp_path / "snippet.py"
    target.write_text((FIX / "w1_bad.py").read_text())
    proc = _cli("--fix", str(target))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = target.read_text()
    assert "jax.devices(" not in out and "jax.device_count(" not in out
    assert "from nonlocalheatequation_tpu.utils.devices import" in out
    # the rewrite is valid python and now lints clean
    ast.parse(out)
    assert _cli(str(target)).returncode == 0


def test_k1_ignores_helper_dicts_in_engine_kwargs(tmp_path):
    pick = tmp_path / "picker.py"
    pick.write_text(
        "class EngineChoice:\n"
        "    def engine_kwargs(self):\n"
        "        labels = {'deadline': self.deadline}  # log helper\n"
        "        return {'method': self.method}\n")
    found = enginekey.check_engine_key(str(ENSEMBLE), str(pick))
    assert found == [], [f.render() for f in found]


def test_cli_runs_k1_on_restricted_ensemble_scan():
    """A path-scoped scan naming ensemble.py must still run the
    never-baselined K1 check (pre-commit-hook shape)."""
    proc = _cli("nonlocalheatequation_tpu/serve/ensemble.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_bad_path_is_usage_error():
    proc = _cli("tools/lint/does_not_exist.py")
    assert proc.returncode == 2
    assert "cannot read" in proc.stderr


def test_cli_fix_keeps_docstring_first(tmp_path):
    """A module with a docstring and no top-level imports: the fixer
    must insert the import BELOW the docstring, not demote it."""
    target = tmp_path / "snippet.py"
    target.write_text(
        '"""Docstring that must stay first."""\n\n\n'
        "def pick():\n"
        "    import jax\n\n"
        "    return jax.devices()[0]\n")
    proc = _cli("--fix", str(target))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = target.read_text()
    assert ast.get_docstring(ast.parse(out)) == \
        "Docstring that must stay first."
    assert "device_list()[0]" in out


def test_k1_unauditable_engine_kwargs_fails_closed(tmp_path):
    pick = tmp_path / "picker.py"
    pick.write_text(
        "class EngineChoice:\n"
        "    def engine_kwargs(self):\n"
        "        kw = {'method': self.method}\n"
        "        return kw\n")
    found = enginekey.check_engine_key(str(ENSEMBLE), str(pick))
    assert any("cannot audit" in f.msg for f in found), \
        [f.render() for f in found]


def test_w3_module_scan_ignores_function_locals():
    """A dtype-inherited module-level scan must not be tainted by an
    unrelated function's f64 local of the same name."""
    src = (
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "out = lax.scan(lambda c, x: (c + x, c), init, xs)\n"
        "def unrelated():\n"
        "    init = jnp.zeros((4,), dtype=jnp.float64)\n"
        "    return init\n")
    found = rules.rule_w3("x.py", src, ast.parse(src), src.splitlines())
    assert found == [], [f.render() for f in found]


def test_cli_fix_skips_grandfathered_findings(tmp_path):
    """--fix must never rewrite a finding the baseline grandfathers as
    deliberately raw (tpu_sanity's probe children are the live case)."""
    target = tmp_path / "probe.py"
    target.write_text("import jax\n\nd = jax.devices()\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([{
        "rule": "W1", "path": str(target.resolve()),
        "code": "d = jax.devices()",
        "reason": "deliberate raw probe in a sacrificial child"}]))
    before = target.read_text()
    proc = _cli("--fix", "--baseline", str(bl), str(target))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "rewrote 0 line(s)" in proc.stdout
    assert target.read_text() == before


def test_cli_fix_merges_partial_devices_import(tmp_path):
    target = tmp_path / "snippet.py"
    target.write_text(
        "import jax\n"
        "from nonlocalheatequation_tpu.utils.devices import device_count\n"
        "\n"
        "n = device_count()\n"
        "d = jax.devices()[0]\n")
    proc = _cli("--fix", str(target))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = target.read_text()
    assert "jax.devices(" not in out
    assert ("from nonlocalheatequation_tpu.utils.devices import "
            "device_count, device_list") in out
    ast.parse(out)


def test_k1_dict_unpacking_is_unauditable(tmp_path):
    pick = tmp_path / "picker.py"
    pick.write_text(
        "class EngineChoice:\n"
        "    def engine_kwargs(self):\n"
        "        return {**self._axes}\n")
    found = enginekey.check_engine_key(str(ENSEMBLE), str(pick))
    assert any("cannot audit" in f.msg for f in found), \
        [f.render() for f in found]


def test_k1_picker_findings_use_report_path(tmp_path):
    """Picker findings must surface under the caller-supplied (repo-
    relative in the CLI) path, not the absolute file read."""
    bad = tmp_path / "picker.py"
    bad.write_text(
        "class EngineChoice:\n"
        "    def engine_kwargs(self):\n"
        "        return 1\n")
    found = enginekey.check_engine_key(
        str(ENSEMBLE), str(bad), picker_rel_path="serve/picker.py")
    assert found and all(f.path == "serve/picker.py" for f in found), \
        [f.render() for f in found]


def test_cli_fix_refuses_aliased_or_multiline_devices_import(tmp_path):
    for body in (
        "import jax\n"
        "from nonlocalheatequation_tpu.utils.devices import (\n"
        "    device_count,\n"
        ")\n\n"
        "d = jax.devices()[0]\n",
        "import jax\n"
        "from nonlocalheatequation_tpu.utils.devices import "
        "device_count as dc\n\n"
        "n = dc()\n"
        "d = jax.devices()[0]\n",
    ):
        target = tmp_path / "snippet.py"
        target.write_text(body)
        proc = _cli("--fix", str(target))
        assert proc.returncode != 0
        assert "by hand" in proc.stdout + proc.stderr
        # the file was not corrupted: still parses, import intact
        ast.parse(target.read_text())


def test_cli_fix_with_no_baseline_still_skips_grandfathered(tmp_path):
    target = tmp_path / "probe.py"
    target.write_text("import jax\n\nd = jax.devices()\n")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([{
        "rule": "W1", "path": str(target.resolve()),
        "code": "d = jax.devices()",
        "reason": "deliberate raw probe in a sacrificial child"}]))
    before = target.read_text()
    proc = _cli("--fix", "--no-baseline", "--baseline", str(bl),
                str(target))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "rewrote 0 line(s)" in proc.stdout
    assert target.read_text() == before


def test_cli_refuses_baselined_k1(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps([{"rule": "K1", "path": "x.py",
                               "code": "c", "reason": "nope"}]))
    proc = _cli("--baseline", str(bl))
    assert proc.returncode == 2
    assert "K1" in proc.stderr
