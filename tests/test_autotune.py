"""Variant autotuner (utils/autotune): mechanics on CPU-interpreted tiny
grids.  The measured numbers are meaningless off-TPU; what these tests pin
is the contract — candidate enumeration respects the fit models, the
winner computes the identical function, caches short-circuit repeated
measurement, and the NLHEAT_AUTOTUNE=1 dispatch actually engages."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from nonlocalheatequation_tpu.ops.nonlocal_op import (
    NonlocalOp2D,
    make_multi_step_fn,
    make_multi_step_fn_base,
)
from nonlocalheatequation_tpu.utils import autotune


@pytest.fixture(autouse=True)
def _fresh_cache(monkeypatch):
    monkeypatch.setattr(autotune, "_memory_cache", {})
    # "" now DISABLES persistence (unset means the per-user default cache
    # since autotune became the on-TPU default) — tests must neither read
    # nor pollute the developer's real tuning record
    monkeypatch.setenv("NLHEAT_AUTOTUNE_CACHE", "")
    # keep CPU-interpreted probes fast
    monkeypatch.setattr(autotune, "PROBE_STEPS", 2)
    monkeypatch.setattr(autotune, "PROBE_ITERS", 1)


def test_candidates_respect_fit_models():
    op = NonlocalOp2D(3, k=1.0, dt=1e-6, dh=1.0 / 48, method="pallas")
    names = [n for n, _ in autotune.candidates(op, (48, 48), 6, jnp.float32)]
    # 48^2 eps=3 fits everything: all four families compete
    assert names[0] == "per-step"
    assert "carried" in names and "resident" in names
    assert "superstep2" in names and "superstep3" in names
    # nsteps < K drops that superstep depth
    names2 = [n for n, _ in autotune.candidates(op, (48, 48), 2, jnp.float32)]
    assert "superstep3" not in names2 and "superstep2" in names2


def test_winner_matches_base_and_cache_short_circuits(monkeypatch, tmp_path):
    op = NonlocalOp2D(3, k=1.0, dt=1e-6, dh=1.0 / 48, method="pallas")
    u = jnp.asarray(np.random.default_rng(0).normal(size=(48, 48)),
                    jnp.float32)
    ref = make_multi_step_fn_base(op, 4, dtype=jnp.float32)(u, jnp.int32(0))

    cache_file = tmp_path / "autotune.json"
    monkeypatch.setenv("NLHEAT_AUTOTUNE_CACHE", str(cache_file))
    calls = []
    real = autotune._measure
    monkeypatch.setattr(
        autotune, "_measure",
        lambda *a, **kw: calls.append(1) or real(*a, **kw))

    fn, winner = autotune.pick_multi_step_fn(op, 4, (48, 48), jnp.float32)
    assert np.array_equal(np.asarray(ref), np.asarray(fn(u, jnp.int32(0))))
    n_measured = len(calls)
    assert n_measured >= 4  # every fitting candidate was timed

    rec = json.loads(cache_file.read_text())
    (key, entry), = rec.items()
    assert entry["winner"] == winner
    assert "per-step" in entry["ms_per_step"]

    # same process: memory cache answers, no re-measurement
    autotune.pick_multi_step_fn(op, 4, (48, 48), jnp.float32)
    assert len(calls) == n_measured
    # fresh process (memory cache cleared): the FILE answers
    monkeypatch.setattr(autotune, "_memory_cache", {})
    autotune.pick_multi_step_fn(op, 4, (48, 48), jnp.float32)
    assert len(calls) == n_measured


def test_dispatch_engages_and_is_bit_identical(monkeypatch):
    op = NonlocalOp2D(3, k=1.0, dt=1e-6, dh=1.0 / 48, method="pallas")
    u = jnp.asarray(np.random.default_rng(1).normal(size=(48, 48)),
                    jnp.float32)
    ref = make_multi_step_fn_base(op, 3, dtype=jnp.float32)(u, jnp.int32(0))
    picked = []
    real = autotune.pick_multi_step_fn
    monkeypatch.setattr(
        autotune, "pick_multi_step_fn",
        lambda *a, **kw: (lambda r: picked.append(r[1]) or r)(real(*a, **kw)))
    monkeypatch.setenv("NLHEAT_AUTOTUNE", "1")
    got = make_multi_step_fn(op, 3, dtype=jnp.float32)(u, jnp.int32(0))
    assert picked, "autotune dispatch did not engage"
    assert np.array_equal(np.asarray(ref), np.asarray(got))


def test_broken_candidate_does_not_win(monkeypatch):
    op = NonlocalOp2D(3, k=1.0, dt=1e-6, dh=1.0 / 48, method="pallas")

    real_cands = autotune.candidates

    def with_broken(op_, shape, nsteps, dtype):
        def broken(o, n, d):
            raise RuntimeError("mosaic rejected this variant")
        return real_cands(op_, shape, nsteps, dtype) + [("broken", broken)]

    monkeypatch.setattr(autotune, "candidates", with_broken)
    fn, winner = autotune.pick_multi_step_fn(op, 3, (48, 48), jnp.float32)
    assert winner != "broken"
    u = jnp.asarray(np.random.default_rng(2).normal(size=(48, 48)),
                    jnp.float32)
    ref = make_multi_step_fn_base(op, 3, dtype=jnp.float32)(u, jnp.int32(0))
    assert np.array_equal(np.asarray(ref), np.asarray(fn(u, jnp.int32(0))))


def test_cached_winner_unfit_falls_back_to_fastest_fitting(monkeypatch):
    """A winner cached from a long segment (superstep3) may not fit a short
    segment (nsteps=2); the entry's recorded rates must then pick the
    fastest candidate that DOES fit — not silently the slowest."""
    import jax

    op = NonlocalOp2D(3, k=1.0, dt=1e-6, dh=1.0 / 48, method="pallas")
    fake = {"per-step": 9.0, "carried": 5.0, "superstep2": 2.0,
            "superstep3": 1.0, "resident": 7.0}
    # seed the memory cache with a fake record (no measurement happens)
    from nonlocalheatequation_tpu import __version__

    key = "/".join([
        f"v{__version__}",  # cache keys carry the package version
        jax.devices()[0].device_kind, "pallas", "48x48", "eps3", "float32"])
    autotune._memory_cache[key] = {
        "winner": "superstep3",
        "ms_per_step": {n: t for n, t in fake.items()},
    }
    fn, winner = autotune.pick_multi_step_fn(op, 2, (48, 48), jnp.float32)
    assert winner == "superstep2"  # fastest of the still-fitting set
    u = jnp.asarray(np.random.default_rng(3).normal(size=(48, 48)),
                    jnp.float32)
    ref = make_multi_step_fn_base(op, 2, dtype=jnp.float32)(u, jnp.int32(0))
    assert np.array_equal(np.asarray(ref), np.asarray(fn(u, jnp.int32(0))))


def test_entry_missing_fitting_candidate_triggers_remeasure(monkeypatch):
    """ADVICE r4: the cache key omits nsteps (probe rates are
    nsteps-invariant), but the candidate SET is not — an entry recorded at
    a short segment (superstep3 never probed) must not pin a longer
    segment to that subset; the missing fitting candidate forces a
    re-measure, after which shorter calls reuse the superset entry."""
    op = NonlocalOp2D(3, k=1.0, dt=1e-6, dh=1.0 / 48, method="pallas")
    calls = []
    real = autotune._measure
    monkeypatch.setattr(
        autotune, "_measure",
        lambda *a, **kw: calls.append(1) or real(*a, **kw))

    autotune.pick_multi_step_fn(op, 2, (48, 48), jnp.float32)
    n_short = len(calls)
    assert n_short == 4  # per-step, carried, superstep2, resident

    # superstep3 fits nsteps=6 but was never probed -> probe ONLY it and
    # merge (prior rates are nsteps-invariant; re-probing them would burn
    # heal-window compile budget on the real chip)
    autotune.pick_multi_step_fn(op, 6, (48, 48), jnp.float32)
    assert len(calls) == n_short + 1

    # the entry now covers every subset: both lengths reuse it
    autotune.pick_multi_step_fn(op, 2, (48, 48), jnp.float32)
    autotune.pick_multi_step_fn(op, 6, (48, 48), jnp.float32)
    assert len(calls) == n_short + 1


def test_errored_probe_in_file_cache_is_retried_once_per_process(
        monkeypatch, tmp_path):
    """A probe that errored in ANOTHER process (None timing in the file
    cache) may have hit a transient wedge window — it must be retried
    once here, not pinned out for the lifetime of the version key.
    In-process failures stay cached (no same-process retry loop)."""
    import jax

    from nonlocalheatequation_tpu import __version__

    op = NonlocalOp2D(3, k=1.0, dt=1e-6, dh=1.0 / 48, method="pallas")
    key = "/".join([
        f"v{__version__}",
        jax.devices()[0].device_kind, "pallas", "48x48", "eps3", "float32"])
    # a prior process measured everything but 'resident' errored there
    cache_file = tmp_path / "autotune.json"
    entry = {"winner": "per-step", "ms_per_step": {
        "per-step": 1.0, "carried": 2.0, "superstep2": 3.0,
        "superstep3": 4.0, "resident": None,
        "resident_error": "RuntimeError: transient tunnel drop"}}
    cache_file.write_text(json.dumps({key: entry}))
    monkeypatch.setenv("NLHEAT_AUTOTUNE_CACHE", str(cache_file))

    probed = []
    real = autotune._measure
    monkeypatch.setattr(
        autotune, "_measure",
        lambda maker, op_, shape, dtype:
        probed.append(shape) or real(maker, op_, shape, dtype))
    autotune.pick_multi_step_fn(op, 6, (48, 48), jnp.float32)
    assert len(probed) == 1  # exactly the errored candidate, nothing else
    rec = json.loads(cache_file.read_text())
    assert isinstance(rec[key]["ms_per_step"]["resident"], float)

    # same process, same key: no further probing
    autotune.pick_multi_step_fn(op, 6, (48, 48), jnp.float32)
    assert len(probed) == 1


def test_default_policy_is_backend_gated(monkeypatch):
    """VERDICT r3 #2: autotune is the on-TPU production default.  Unset env
    on CPU must keep the plain base path (tests/CLI smoke unaffected);
    NLHEAT_AUTOTUNE=0 must force it off everywhere."""
    op = NonlocalOp2D(3, k=1.0, dt=1e-6, dh=1.0 / 48, method="pallas")
    monkeypatch.delenv("NLHEAT_AUTOTUNE", raising=False)
    fn = make_multi_step_fn(op, 3, dtype=jnp.float32)
    assert fn.__name__ != "multi_autotuned"  # cpu backend: default off

    import jax

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    fn = make_multi_step_fn(op, 3, dtype=jnp.float32)
    assert fn.__name__ == "multi_autotuned"  # tpu: default on
    monkeypatch.setenv("NLHEAT_AUTOTUNE", "0")
    fn = make_multi_step_fn(op, 3, dtype=jnp.float32)
    assert fn.__name__ != "multi_autotuned"  # pinned off (bench rungs)
    # manual variant knobs pin their variant: the default must yield
    monkeypatch.delenv("NLHEAT_AUTOTUNE", raising=False)
    monkeypatch.setenv("NLHEAT_SUPERSTEP", "2")
    fn = make_multi_step_fn(op, 3, dtype=jnp.float32)
    assert fn.__name__ != "multi_autotuned"


def test_default_cache_path_is_per_user(monkeypatch, tmp_path):
    monkeypatch.delenv("NLHEAT_AUTOTUNE_CACHE", raising=False)
    monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
    path = autotune._cache_path()
    assert path == str(tmp_path / "nlheat" / "autotune.json")
    monkeypatch.setenv("NLHEAT_AUTOTUNE_CACHE", "")
    assert autotune._cache_path() is None


def test_3d_dispatch_engages_and_is_bit_identical(monkeypatch):
    from nonlocalheatequation_tpu.ops.nonlocal_op import NonlocalOp3D

    op = NonlocalOp3D(2, k=1.0, dt=1e-7, dh=1.0 / 16, method="pallas")
    u = jnp.asarray(np.random.default_rng(2).normal(size=(16, 16, 16)),
                    jnp.float32)
    ref = make_multi_step_fn_base(op, 2, dtype=jnp.float32)(u, jnp.int32(0))
    picked = []
    real = autotune.pick_multi_step_fn
    monkeypatch.setattr(
        autotune, "pick_multi_step_fn",
        lambda *a, **kw: (lambda r: picked.append(r[1]) or r)(real(*a, **kw)))
    monkeypatch.setenv("NLHEAT_AUTOTUNE", "1")
    got = make_multi_step_fn(op, 2, dtype=jnp.float32)(u, jnp.int32(0))
    assert picked, "3D autotune dispatch did not engage"
    assert np.array_equal(np.asarray(ref), np.asarray(got))
    # the candidate set includes the 3D variants
    names = [n for n, _ in autotune.candidates(op, (16, 16, 16), 2,
                                               jnp.float32)]
    assert "carried3d" in names
